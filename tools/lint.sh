#!/usr/bin/env bash
# lint.sh — the local one-liner for the graft-lint suite (ci.sh runs
# the same thing as stage 0).
# Usage: tools/lint.sh [--json] [--changed] [paths...]
#   --changed : lint only git-modified files + their table anchors —
#               the fast pre-commit path (full tree stays the ci gate)
set -u
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python tools/graft_lint/run.py "$@"
