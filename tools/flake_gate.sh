#!/usr/bin/env bash
# flake_gate.sh — the standing deflake check (VERDICT r5 "Next round" #5):
# run the tier-1 suite twice back-to-back and diff the failure sets.
#
#   tests failing in BOTH runs   -> real breakage (reported, exit 1)
#   tests failing in ONE run only -> flakes (reported, exit 2)
#   identical green runs          -> exit 0
#
# Stable failures matching tools/timing_sensitive.txt get ONE more
# chance: an automatic re-run of just that test in ISOLATION (the
# documented 2-core-host load-flakiness protocol, previously manual) —
# a pass there reclassifies the failure as a load flake (exit 2, not
# 1); a second red in isolation stays a regression.
#
# Usage:  tools/flake_gate.sh [extra pytest args...]
# The tier-1 invocation mirrors ROADMAP.md's "Tier-1 verify" line.

set -u
cd "$(dirname "$0")/.."

run_dir=$(mktemp -d /tmp/flake_gate.XXXXXX)
trap 'rm -rf "$run_dir"' EXIT

tier1() {
    local log="$1"; shift
    timeout -k 10 870 env JAX_PLATFORMS=cpu \
        python -m pytest tests/ -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly "$@" 2>&1 | tee "$log" >/dev/null
}

fails() {  # FAILED/ERROR node ids from a pytest -q log, sorted
    grep -aE '^(FAILED|ERROR) ' "$1" | awk '{print $2}' | sort -u
}

echo "flake gate: run 1/2..."
tier1 "$run_dir/run1.log" "$@"
echo "flake gate: run 2/2..."
tier1 "$run_dir/run2.log" "$@"

fails "$run_dir/run1.log" > "$run_dir/f1"
fails "$run_dir/run2.log" > "$run_dir/f2"

stable=$(comm -12 "$run_dir/f1" "$run_dir/f2")
flaky=$(comm -3 "$run_dir/f1" "$run_dir/f2" | tr -d '\t' | sort -u)

for log in 1 2; do
    tail -1 "$run_dir/run$log.log" | sed "s/^/run $log: /"
done

# -- known-timing-sensitive protocol: stable failures matching
# tools/timing_sensitive.txt re-run ALONE before counting as
# regressions (a quiet-host single-test run is the documented
# discriminator between a load flake and real breakage)
if [ -n "$stable" ] && [ -f tools/timing_sensitive.txt ]; then
    patterns=$(grep -vE '^[[:space:]]*(#|$)' tools/timing_sensitive.txt)
    if [ -n "$patterns" ]; then
        kept=""
        while IFS= read -r nodeid; do
            [ -n "$nodeid" ] || continue
            if echo "$nodeid" | grep -qE -f <(echo "$patterns"); then
                echo "flake gate: '$nodeid' is a known" \
                     "timing-sensitive test — re-running in isolation..."
                if timeout -k 10 300 env JAX_PLATFORMS=cpu \
                    python -m pytest "tests/${nodeid#tests/}" -q \
                    -p no:cacheprovider -p no:xdist -p no:randomly \
                    > "$run_dir/iso.log" 2>&1; then
                    echo "flake gate:   passed in isolation ->" \
                         "reclassified as a load flake"
                    flaky=$(printf '%s\n%s' "$flaky" "$nodeid" | sort -u)
                    continue
                fi
                echo "flake gate:   STILL FAILS in isolation ->" \
                     "a real regression"
                tail -5 "$run_dir/iso.log" | sed 's/^/    /'
            fi
            kept=$(printf '%s\n%s' "$kept" "$nodeid")
        done <<< "$stable"
        stable=$(echo "$kept" | sed '/^$/d')
    fi
fi

rc=0
if [ -n "$stable" ]; then
    echo "STABLE FAILURES (both runs):"
    echo "$stable" | sed 's/^/  /'
    rc=1
fi
if [ -n "$flaky" ]; then
    echo "FLAKY (failed in exactly one run):"
    echo "$flaky" | sed 's/^/  /'
    [ $rc -eq 0 ] && rc=2
fi
[ $rc -eq 0 ] && echo "flake gate: two consecutive identical green runs"
exit $rc
