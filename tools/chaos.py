#!/usr/bin/env python3
"""chaos.py — the failure-containment proof harness (ISSUE 9).

Scenario runner over a MANAGED disperse 4+2 volume (glusterd + six
real brick subprocesses, I/O through the full wire stack): each
scenario breaks the cluster a specific way and asserts the degraded
contract the EC/protocol planes promise:

* ``degraded_read``   — SIGKILL a brick mid-write: every write still
                        lands (5/6 >= quorum), every read with the
                        brick down is byte-identical, the restarted
                        brick heals to convergence (heal-count -> 0),
                        and a read forced THROUGH the healed brick
                        (disperse.ec-read-mask) is byte-identical.
* ``quorum_write``    — SIGKILL R+1 bricks: writes fail CLEANLY
                        (FopError, bounded time, no hang), and after
                        restart + heal no torn state is visible — the
                        pre-kill file is byte-identical and the failed
                        write's target either errors or reads back
                        exactly what was attempted.
* ``blackhole``       — SIGSTOP a brick (transport alive, nothing
                        answers): reads complete degraded within a
                        bound (ping-timeout + failfast drop, never a
                        call-timeout serial crawl), byte-identical.
* ``error_storm``     — debug.error-gen in deterministic
                        failure-count mode on a brick's readv: reads
                        stay byte-identical while the injected
                        failures burn down, and the budget is exact.
* ``delay_storm``     — debug.delay-gen on every brick's readv:
                        reads stay correct and bounded.
* ``gateway``         — the HTTP front door over the same volume —
                        served by a workers=2 shared-nothing pool —
                        keeps answering (correct bytes or clean
                        error, never a hang) while a brick is down,
                        and a worker SIGKILL mid-load never drops
                        the volume (supervisor respawn, ISSUE 12).
* ``lease_storm``     — leased readers vs a hot writer (ISSUE 16):
                        every overwrite recalls every holder within a
                        bound, every holder returns voluntarily (a
                        revocation would poison the next grant), every
                        post-recall read is byte-exact, and a holder
                        that dies WITHOUT releasing is reaped at
                        disconnect instead of stalling the writer for
                        the recall grace.
* ``qos_storm``       — a greedy flooder vs a polite reader on the
                        same volume (ISSUE 17): with server.qos off
                        the flood runs unshaped (baseline); a LIVE
                        volume-set flip arms per-client token buckets
                        and the greedy client's throughput drops
                        measurably while the polite client's p99 stays
                        bounded and error-free, THROTTLE_START lands
                        in eventsd history, and the shaping shows in
                        volume status clients.
* ``rebalance_grow``  — grow the loaded 4+2 volume by a second
                        distribute leg WHILE serving: managed daemon
                        migration under live reads/writes, SIGKILL +
                        respawn resumes from its checkpoint, bounded
                        read latency, every pre-existing and
                        in-flight object byte-identical after
                        convergence (ISSUE 11 acceptance).
* ``fuse``            — (--with-fuse only; kernel-dependent) the
                        mount stays responsive through a brick kill.

Every scenario is wall-clock bounded (a hang IS a failure), and the
run reports leaked threads/tasks against a warmed baseline — the
containment plane must not pay for failure handling with leaks.

``--baseline FILE`` loads a previous ``--json`` report and judges this
run's timing rows against it at the documented 2-core swing band
(ISSUE 20) — machine-readable ``regressions: [...]`` rows land in the
report, the mirror of bench.py's throughput gate.

Usage:
    python tools/chaos.py [--scenario NAME ...] [--json] [--with-fuse]
                          [--baseline FILE]
Exit 0 iff every selected scenario passed and nothing leaked.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import signal
import sys
import tempfile
import threading
import time
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np  # noqa: E402

from glusterfs_tpu.core.fops import FopError  # noqa: E402
from glusterfs_tpu.core.layer import walk  # noqa: E402
from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,  # noqa: E402
                                         mount_volume)

K, R = 4, 2
N = K + R
MIB = 1 << 20

#: per-scenario wall-clock bound (a wedged scenario FAILS, it never
#: hangs the harness); sized for rebalance_grow, which spawns six
#: extra bricks plus two rebalance daemons on a loaded host
SCENARIO_DEADLINE_S = 420.0

SCENARIOS: dict = {}


def scenario(name: str):
    def deco(fn):
        SCENARIOS[name] = fn
        return fn
    return deco


def payload_for(i: int, mib: int = 1) -> bytes:
    return np.random.default_rng(1000 + i).integers(
        0, 256, mib * MIB, dtype=np.uint8).tobytes()


class Stack:
    """One managed disperse 4+2 stack: glusterd + 6 brick subprocesses
    + helpers to break and mend them."""

    def __init__(self, base: str, name: str = "chaos"):
        self.base = base
        self.name = name
        self.d: Glusterd | None = None

    async def __aenter__(self):
        self.d = Glusterd(os.path.join(self.base, "gd"))
        await self.d.start()
        async with MgmtClient(self.d.host, self.d.port) as c:
            await c.call("volume-create", name=self.name,
                         vtype="disperse", redundancy=R,
                         bricks=[{"path": os.path.join(self.base,
                                                       f"b{i}")}
                                 for i in range(N)])
            await c.call("volume-start", name=self.name)
        return self

    async def __aexit__(self, *exc):
        await self.d.stop()

    async def set(self, key: str, value: str) -> None:
        async with MgmtClient(self.d.host, self.d.port) as c:
            await c.call("volume-set", name=self.name, key=key,
                         value=value)

    async def mount(self):
        cl = await mount_volume(self.d.host, self.d.port, self.name)
        # calibrate the codec router off the clock (its first device
        # probe pays jax imports that would eat a scenario's bound)
        for layer in walk(cl.graph.top):
            cal = getattr(getattr(layer, "codec", None),
                          "ensure_calibrated", None)
            if cal is not None:
                await cal()
        return cl

    def brick_name(self, i: int) -> str:
        return f"{self.name}-brick-{i}"

    def kill_brick(self, i: int, sig=signal.SIGKILL) -> int:
        """SIGKILL brick i; returns the port it was serving (for the
        same-port respawn clients expect)."""
        bname = self.brick_name(i)
        proc = self.d.bricks.pop(bname)
        port = self.d.ports.pop(bname)
        os.kill(proc.pid, sig)
        proc.wait()
        return port

    def pause_brick(self, i: int) -> None:
        os.kill(self.d.bricks[self.brick_name(i)].pid, signal.SIGSTOP)

    def resume_brick(self, i: int) -> None:
        os.kill(self.d.bricks[self.brick_name(i)].pid, signal.SIGCONT)

    async def restart_brick(self, i: int, port: int) -> None:
        vol = self.d._vol(self.name)
        b = next(x for x in vol["bricks"]
                 if x["name"] == self.brick_name(i))
        await self.d._spawn_brick(vol, b, port=port)

    async def heal_until_converged(self, timeout: float = 120.0) -> dict:
        """heal full, then poll heal-count to 0 (convergence proof)."""
        res = await self.d.op_volume_heal(self.name, "full")
        deadline = time.monotonic() + timeout
        while True:
            hc = await self.d.op_volume_heal_count(self.name)
            if hc.get("total", -1) == 0 and "partial" not in hc:
                return {"healed": res, "heal_count": hc["total"]}
            if time.monotonic() > deadline:
                raise TimeoutError(f"heal never converged: {hc}")
            await asyncio.sleep(1.0)


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


@scenario("degraded_read")
async def degraded_read(base: str, opts) -> dict:
    """Brick SIGKILL mid-write -> degraded byte-identical reads ->
    restart -> heal converges -> the healed brick serves reads.
    The incident plane rides along: the kill must auto-capture a
    bundle (BRICK_DISCONNECTED is failure-class), the cluster capture
    must show one trace id spanning >=2 distinct processes, and the
    incident dir must respect its size bound."""
    out: dict = {}
    n_files = 6
    victim = 2
    async with Stack(base) as st:
        inc_dir = os.path.join(base, "incidents")
        await st.set("diagnostics.incident-dir", inc_dir)
        await st.set("diagnostics.incident-max-bytes", "8MB")
        await st.set("diagnostics.incident-min-interval", "0")
        cl = await st.mount()
        try:
            pay = [payload_for(i) for i in range(n_files)]
            # writes in flight when the brick dies: the kill lands
            # mid-stream, not between fops
            writes = [asyncio.ensure_future(
                cl.write_file(f"/f{i}", pay[i])) for i in range(n_files)]
            await asyncio.sleep(0.3)
            port = st.kill_brick(victim)
            out["killed_mid_write"] = sum(1 for w in writes
                                          if not w.done())
            await asyncio.gather(*writes)
            # degraded reads: one brick down, byte-identical
            datas = await asyncio.gather(*(cl.read_file(f"/f{i}")
                                           for i in range(n_files)))
            assert all(bytes(d) == p for d, p in zip(datas, pay)), \
                "degraded read parity broken"
            out["degraded_reads_ok"] = n_files
            # the kill auto-captured a local bundle: the mounted
            # client saw BRICK_DISCONNECTED (failure-class) and wrote
            # its flight ring into the incident dir
            caps: list = []
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                caps = [f for f in (os.listdir(inc_dir)
                                    if os.path.isdir(inc_dir) else [])
                        if "BRICK_DISCONNECTED" in f]
                if caps:
                    break
                await asyncio.sleep(0.2)
            assert caps, "brick SIGKILL never auto-captured a bundle"
            out["auto_captured"] = len(caps)
            # cluster capture: the merged bundle holds >=1 trace id
            # whose spans come from >=2 distinct PROCESSES (the
            # degraded reads fanned one client trace across bricks)
            cluster = await st.d.op_volume_incident_capture(st.name)
            with open(cluster["bundle"]) as f:
                merged = json.load(f)
            pids_by_tid: dict = {}
            for proc_bundle in merged["processes"].values():
                if not isinstance(proc_bundle, dict) \
                        or "spans" not in proc_bundle:
                    continue
                for sp in proc_bundle["spans"]:
                    pids_by_tid.setdefault(sp["trace"], set()).add(
                        proc_bundle["pid"])
            shared = [t for t, pids in pids_by_tid.items()
                      if len(pids) >= 2]
            assert shared, "no trace id spans two processes"
            out["cross_process_traces"] = len(shared)
            # leak audit, incident-dir edition: captures + the
            # cluster bundle stay inside the configured size bound
            total = sum(os.path.getsize(os.path.join(inc_dir, f))
                        for f in os.listdir(inc_dir))
            assert total <= 8 * MIB, \
                f"incident dir exceeded its size bound ({total}B)"
            out["incident_dir_bytes"] = total
            # restart + heal to convergence
            await st.restart_brick(victim, port)
            conv = await st.heal_until_converged()
            out["heal_count_after"] = conv["heal_count"]
        finally:
            await cl.unmount()
        # the healed brick must actually SERVE: force it into the
        # read set (ec-read-mask is strict) with exactly K ids
        mask = ",".join(str(i) for i in
                        [victim] + [i for i in range(N)
                                    if i != victim][:K - 1])
        await st.set("disperse.ec-read-mask", mask)
        cl2 = await st.mount()
        try:
            datas = await asyncio.gather(*(cl2.read_file(f"/f{i}")
                                           for i in range(n_files)))
            assert all(bytes(d) == p for d, p in zip(datas, pay)), \
                "post-heal read through the healed brick broke parity"
            out["healed_brick_serves"] = True
        finally:
            await cl2.unmount()
    return out


@scenario("quorum_write")
async def quorum_write(base: str, opts) -> dict:
    """R+1 bricks dead -> writes fail cleanly; after restart + heal
    nothing torn is visible."""
    out: dict = {}
    async with Stack(base) as st:
        cl = await st.mount()
        pre = payload_for(100)
        attempted = payload_for(101)
        ports = {}
        try:
            await cl.write_file("/pre", pre)
            # make /pre DURABLE before the blast: fsync forces the
            # eager window's version/size commit onto all six bricks.
            # Without it the deferred post-op would reach only the
            # three survivors — a below-K version split that is
            # legitimately unhealable once the others return (a
            # non-fsynced write's durability is quorum-best-effort,
            # here we are testing the durable file's contract)
            f = await cl.open("/pre", os.O_RDWR)
            await f.fsync()
            await f.close()
            for i in range(R + 1):   # 3 dead of 6: 3 < K=4
                ports[i] = st.kill_brick(i)
            t0 = time.monotonic()
            try:
                await asyncio.wait_for(cl.write_file("/torn", attempted),
                                       60)
                raise AssertionError(
                    "below-quorum write succeeded (3/6 bricks up)")
            except FopError as e:
                out["write_failed_cleanly"] = repr(e)[:120]
            out["fail_latency_s"] = round(time.monotonic() - t0, 2)
        finally:
            await cl.unmount()
        for i, port in ports.items():
            await st.restart_brick(i, port)
        conv = await st.heal_until_converged()
        out["heal_count_after"] = conv["heal_count"]
        cl2 = await st.mount()
        try:
            got = await cl2.read_file("/pre")
            assert bytes(got) == pre, "pre-kill file torn after recovery"
            out["pre_file_intact"] = True
            # the failed write must not be VISIBLY torn: either a clean
            # error, or exactly the attempted bytes (had it reached
            # quorum after all) — never a mangled in-between
            try:
                got = await asyncio.wait_for(cl2.read_file("/torn"), 60)
                assert bytes(got) == attempted, \
                    "failed write left torn bytes visible"
                out["failed_write_state"] = "complete"
            except FopError as e:
                out["failed_write_state"] = f"clean error {e.err}"
        finally:
            await cl2.unmount()
    return out


@scenario("blackhole")
async def blackhole(base: str, opts) -> dict:
    """SIGSTOP a brick: the transport stays up but answers nothing —
    ping-timeout + disconnect failfast turn it into a bounded degrade,
    not a call-timeout crawl."""
    out: dict = {}
    victim = 1
    async with Stack(base) as st:
        cl = await st.mount()
        try:
            pay = payload_for(200)
            await cl.write_file("/bh", pay)
            st.pause_brick(victim)
            try:
                t0 = time.monotonic()
                # several reads: the FIRST eats the ping-timeout
                # detection window, the rest ride the dropped child
                for _ in range(3):
                    got = await asyncio.wait_for(cl.read_file("/bh"), 60)
                    assert bytes(got) == pay, "blackhole read parity"
                dt = time.monotonic() - t0
                out["blackhole_3_reads_s"] = round(dt, 2)
                assert dt < 45, f"blackhole reads not bounded: {dt:.1f}s"
                # a write through the same hole also completes (5/6)
                await asyncio.wait_for(
                    cl.write_file("/bh2", pay[:256 * 1024]), 60)
                out["blackhole_write_ok"] = True
            finally:
                st.resume_brick(victim)
        finally:
            await cl.unmount()
    return out


@scenario("error_storm")
async def error_storm(base: str, opts) -> dict:
    """debug.error-gen deterministic failure-count storm: every
    brick's readv fails exactly N times, then passes.  While the
    budget burns a read either succeeds byte-identical or fails
    CLEANLY within its bound (never a hang, never wrong bytes); once
    it is spent — deterministically, no probability/seed tuning —
    reads recover and STAY byte-identical."""
    out: dict = {}
    async with Stack(base) as st:
        cl = await st.mount()
        try:
            pay = payload_for(300)
            await cl.write_file("/es", pay)
        finally:
            await cl.unmount()
        # arm the storm: exactly 4 readv failures per brick, then pass
        await st.set("debug.error-gen", "on")
        await st.set("debug.error-fops", "readv")
        await st.set("debug.error-number", "EIO")
        await st.set("debug.error-failure-count", "4")
        cl = await st.mount()
        try:
            clean_failures = 0
            recovered_at = None
            streak = 0
            for i in range(24):
                try:
                    got = await asyncio.wait_for(cl.read_file("/es"), 60)
                    assert bytes(got) == pay, \
                        "error-storm served WRONG bytes"
                    streak += 1
                    if recovered_at is None:
                        recovered_at = i
                    if streak >= 5:
                        break
                except FopError:
                    clean_failures += 1
                    streak = 0
                    recovered_at = None
            assert streak >= 5, \
                f"reads never recovered after the deterministic " \
                f"budget ({clean_failures} failures)"
            out["clean_failures_during_storm"] = clean_failures
            out["recovered_at_attempt"] = recovered_at
        finally:
            await cl.unmount()
        await st.set("debug.error-gen", "off")
    return out


@scenario("delay_storm")
async def delay_storm(base: str, opts) -> dict:
    """debug.delay-gen on every brick's readv: correctness and a
    bounded completion under injected latency."""
    out: dict = {}
    async with Stack(base) as st:
        cl = await st.mount()
        try:
            pay = payload_for(400)
            await cl.write_file("/ds", pay)
        finally:
            await cl.unmount()
        await st.set("debug.delay-gen", "on")
        await st.set("debug.delay-fops", "readv")
        await st.set("debug.delay-duration", "200000")  # 200ms
        await st.set("debug.delay-percent", "100")
        cl = await st.mount()
        try:
            t0 = time.monotonic()
            got = await asyncio.wait_for(cl.read_file("/ds"), 90)
            dt = time.monotonic() - t0
            assert bytes(got) == pay, "delay-storm read parity"
            out["delayed_read_s"] = round(dt, 2)
        finally:
            await cl.unmount()
        await st.set("debug.delay-gen", "off")
    return out


@scenario("gateway")
async def gateway(base: str, opts) -> dict:
    """The HTTP front door stays responsive while a brick is down —
    now against a ``workers=2`` shared-nothing pool (ISSUE 12): the
    supervisor subprocess owns the port, two worker processes serve
    it, a brick SIGKILL degrades GETs byte-identically, and a WORKER
    SIGKILL mid-load never drops the volume (the supervisor respawns,
    the sibling keeps serving)."""
    import subprocess

    from glusterfs_tpu.gateway.minihttp import fetch as http

    out: dict = {}
    async with Stack(base) as st:
        async with MgmtClient(st.d.host, st.d.port) as c:
            spec = await c.call("getspec", name=st.name)
        volfile = os.path.join(base, "gw-client.vol")
        with open(volfile, "w") as f:
            f.write(spec["volfile"])
        portfile = os.path.join(base, "gw.port")
        statusfile = os.path.join(base, "gw.status")
        inc_dir = os.path.join(base, "incidents")
        import socket

        with socket.socket() as _s:  # ephemeral metrics port
            _s.bind(("127.0.0.1", 0))
            mport = _s.getsockname()[1]
        env = dict(os.environ)
        sup = subprocess.Popen(
            [sys.executable, "-m", "glusterfs_tpu.gateway",
             "--volfile", volfile, "--workers", "2", "--pool", "2",
             "--portfile", portfile, "--statusfile", statusfile,
             "--max-clients", "128", "--metrics-port", str(mport),
             "--incident-dir", inc_dir],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        try:
            deadline = time.monotonic() + 120
            while not os.path.exists(portfile):
                assert sup.poll() is None, "gateway supervisor died"
                assert time.monotonic() < deadline, \
                    "worker pool never came up"
                await asyncio.sleep(0.2)
            with open(portfile) as f:
                gw_port = int(f.read())
            with open(statusfile) as f:
                wst = json.load(f)
            out["workers_mode"] = wst["mode"]
            assert len(wst["workers"]) == 2
            body = payload_for(500, 1)[:512 * 1024]
            s, _, _ = await http("127.0.0.1", gw_port, "PUT", "/b")
            assert s == 200, s
            s, _, _ = await http("127.0.0.1", gw_port, "PUT",
                                 "/b/obj", body=body)
            assert s == 200, s
            # let the EC eager window's deferred size commit land
            # before breaking things: without a read lease settling it
            # (features/leases, lease_storm below), cross-pool-client
            # read-after-PUT coherence is bounded by the post-op delay
            # (~eager-lock-timeout), and THIS scenario measures
            # degraded responsiveness, not that window
            deadline = time.monotonic() + 10
            while True:
                s, _, data = await http("127.0.0.1", gw_port, "GET",
                                        "/b/obj")
                if s == 200 and data == body:
                    break
                assert time.monotonic() < deadline, \
                    f"healthy GET never settled ({s}, {len(data)}B)"
                await asyncio.sleep(0.3)
            port = st.kill_brick(3)
            t0 = time.monotonic()
            s, _, data = await asyncio.wait_for(
                http("127.0.0.1", gw_port, "GET", "/b/obj"), 60)
            assert s == 200 and data == body, \
                f"degraded gateway GET broke ({s})"
            out["degraded_get_s"] = round(time.monotonic() - t0, 2)
            s, _, _ = await asyncio.wait_for(
                http("127.0.0.1", gw_port, "PUT", "/b/obj2",
                     body=body[:64 * 1024]), 60)
            assert s in (200, 503), f"degraded PUT hung or broke ({s})"
            out["degraded_put_status"] = s
            await st.restart_brick(3, port)

            # worker kill MID-LOAD: a steady GET stream keeps running
            # while one worker dies — the volume (and the pool's port)
            # must keep answering right bytes; the supervisor respawns
            served = {"ok": 0, "refused": 0}
            stop_load = asyncio.Event()

            async def load():
                while not stop_load.is_set():
                    try:
                        s, _, d = await asyncio.wait_for(
                            http("127.0.0.1", gw_port, "GET",
                                 "/b/obj"), 30)
                        if s == 200 and d == body:
                            served["ok"] += 1
                        else:
                            served["refused"] += 1
                    except (ConnectionError, OSError,
                            asyncio.TimeoutError):
                        served["refused"] += 1
                    await asyncio.sleep(0.05)

            loader = asyncio.ensure_future(load())
            await asyncio.sleep(0.5)
            victim = wst["workers"][0]["pid"]
            os.kill(victim, signal.SIGKILL)
            t0 = time.monotonic()
            respawned = False
            while time.monotonic() - t0 < 30:
                with open(statusfile) as f:
                    wst2 = json.load(f)
                if wst2["respawns"] >= 1 and \
                        all(w["alive"] for w in wst2["workers"]):
                    respawned = True
                    break
                await asyncio.sleep(0.3)
            await asyncio.sleep(1.0)  # load rides the respawned pool
            stop_load.set()
            await loader
            assert respawned, "killed worker never respawned"
            assert served["ok"] >= 5, \
                f"volume dropped under worker kill: {served}"
            out["worker_kill_respawn_s"] = round(
                time.monotonic() - t0, 2)
            out["worker_kill_load"] = dict(served)

            # the respawn is a failure-class event: the supervisor must
            # have auto-captured an incident bundle into --incident-dir
            bundle = None
            t0 = time.monotonic()
            while time.monotonic() - t0 < 15:
                hits = [f for f in sorted(os.listdir(inc_dir))
                        if "GATEWAY_WORKER_RESPAWN" in f] \
                    if os.path.isdir(inc_dir) else []
                if hits:
                    bundle = hits[-1]
                    break
                await asyncio.sleep(0.3)
            assert bundle, "worker respawn did not auto-capture an " \
                "incident bundle"
            out["auto_captured"] = bundle

            # cross-process trace stitch: a GET's trace id minted in a
            # gateway WORKER must also appear in a BRICK daemon's span
            # ring (the wire trace element crossed the client graph)
            s, _, d = await asyncio.wait_for(
                http("127.0.0.1", mport, "GET", "/incident.json"), 15)
            assert s == 200, f"/incident.json -> {s}"
            sup_bundle = json.loads(d)
            worker_tids = {sp.get("trace")
                           for w_ in sup_bundle["workers"]
                           for sp in w_.get("flight", {}).get(
                               "spans", [])} - {None}
            local = await st.d.op_volume_incident_local(st.name)
            brick_tids = set()
            for proc in local["bricks"].values():
                if isinstance(proc, dict):
                    for sp in proc.get("spans") or []:
                        if sp.get("trace"):
                            brick_tids.add(sp["trace"])
            shared = worker_tids & brick_tids
            assert shared, "no trace id spans both a gateway worker " \
                "and a brick process"
            out["cross_process_traces"] = len(shared)
        finally:
            if sup.poll() is None:
                sup.terminate()
                try:
                    await asyncio.to_thread(sup.wait, timeout=10)
                except subprocess.TimeoutExpired:
                    sup.kill()
    return out


@scenario("lease_storm")
async def lease_storm(base: str, opts) -> dict:
    """Leased readers vs a hot writer over the managed volume (ISSUE
    16): recalls fan in bounded and voluntary, post-recall reads are
    byte-exact, re-grants keep working round after round (revocation
    would poison them), and a holder that dies without releasing is
    reaped at disconnect instead of stalling the writer."""
    out: dict = {}
    n_readers, rounds = 6, 3
    hot = 48 * 1024
    async with Stack(base) as st:
        # leases are volgen-gated off by default; flipping them on is a
        # graph-shape change -> bricks respawn with the layer.  The
        # long recall grace makes the reap assertion sharp: a holder
        # that is NOT returned/reaped costs 10s, visibly over bound.
        await st.set("features.leases", "on")
        await st.set("features.lease-recall-timeout", "10")
        await st.set("features.lease-timeout", "600")   # v15 key
        w = await st.mount()
        readers = [await st.mount() for _ in range(n_readers)]
        victim = None
        try:
            body = payload_for(7)[:hot]
            await w.write_file("/hot", body)
            write_s = []
            for rnd in range(rounds):
                for r in readers:
                    assert await r.lease_acquire("/hot"), \
                        "re-grant refused: a voluntary return poisoned"
                    assert bytes(await r.read_file("/hot")) == body
                body = payload_for(100 + rnd)[:hot]
                t0 = time.monotonic()
                await w.write_file("/hot", body)
                write_s.append(round(time.monotonic() - t0, 2))
                assert write_s[-1] < 8, \
                    f"recall fan-in stalled: {write_s}"
                for r in readers:
                    assert bytes(await r.read_file("/hot")) == body
            assert all(r.lease_recalls >= rounds for r in readers), \
                [r.lease_recalls for r in readers]
            out["write_recall_s"] = write_s
            out["recalls_per_reader"] = rounds

            # a holder that never releases: unmount drops the sockets
            # with the lease still granted; the brick's disconnect reap
            # (release_client) must clear it — the next write completes
            # inside the bound instead of burning the 10s grace
            victim = readers.pop()
            assert await victim.lease_acquire("/hot")
            await victim.unmount()
            victim = None
            await asyncio.sleep(1.0)  # let the reap land
            body = payload_for(999)[:hot]
            t0 = time.monotonic()
            await w.write_file("/hot", body)
            reap_s = time.monotonic() - t0
            assert reap_s < 8, \
                f"dead holder stalled the writer {reap_s:.1f}s"
            out["dead_holder_write_s"] = round(reap_s, 2)
            for r in readers:
                assert bytes(await r.read_file("/hot")) == body
        finally:
            if victim is not None:
                await victim.unmount()
            for r in readers:
                await r.unmount()
            await w.unmount()
    return out


@scenario("qos_storm")
async def qos_storm(base: str, opts) -> dict:
    """Greedy flooder vs polite reader (ISSUE 17): the QoS plane,
    armed by a LIVE volume-set, caps the greedy client per identity —
    its throughput drops vs the unshaped baseline, the polite client
    never errors and its p99 stays bounded, THROTTLE_START reaches
    eventsd, and volume status clients shows the shaping."""
    from glusterfs_tpu.core import events as gf_events
    from glusterfs_tpu.mgmt.eventsd import EventsDaemon

    out: dict = {}
    ev = EventsDaemon()
    udp, _ctl = await ev.start()
    # BEFORE Stack: brick subprocesses inherit the env at spawn
    os.environ["GFTPU_EVENTSD"] = f"127.0.0.1:{udp}"
    gf_events.configure(f"127.0.0.1:{udp}")
    try:
        async with Stack(base) as st:
            greedy = await st.mount()
            polite = await st.mount()
            try:
                # WRITE load: client caches would serve a read flood
                # at zero wire fops (the leased-reader exemption by
                # construction) — writes always meet the admission gate
                body = payload_for(17)[:4096]
                retries = {"greedy": 0, "polite": 0}

                async def phase(seconds: float) -> tuple[float, float]:
                    """(greedy write_file/s, polite p99 seconds) under
                    a sequential greedy flood + a paced polite writer.
                    One bounded retry absorbs the live graph-reload
                    window (the rebalance_grow discipline) — QoS sheds
                    themselves are invisible here, client backoff
                    re-sends them."""
                    stop = asyncio.Event()
                    done = {"n": 0}

                    async def put(cl, path, who) -> None:
                        try:
                            await cl.write_file(path, body)
                        except FopError:
                            retries[who] += 1
                            await cl.write_file(path, body)

                    async def flood(i: int):
                        # 4-way concurrency on distinct paths: greedy
                        # means MORE OUTSTANDING WORK, not merely a
                        # tighter loop — and no lock contention noise
                        while not stop.is_set():
                            await put(greedy, f"/g{i}", "greedy")
                            done["n"] += 1

                    ft = [asyncio.create_task(flood(i))
                          for i in range(4)]
                    lat: list[float] = []
                    t0 = time.monotonic()
                    while time.monotonic() - t0 < seconds:
                        s = time.monotonic()
                        await put(polite, "/p", "polite")
                        lat.append(time.monotonic() - s)
                        await asyncio.sleep(0.15)  # ~5/s: in budget
                    stop.set()
                    await asyncio.gather(*ft)
                    lat.sort()
                    return (done["n"] / seconds,
                            lat[int(0.99 * (len(lat) - 1))])

                g_off, p99_off = await phase(4.0)

                # LIVE flip — no remount, no brick respawn: the watcher
                # reconfigures the running server tops and the very
                # next frames meet the buckets
                await st.set("server.qos-fops-per-sec", "60")
                await st.set("server.qos-burst", "1")
                await st.set("server.qos", "on")
                await asyncio.sleep(1.5)  # volfile watcher propagation

                g_on, p99_on = await phase(6.0)
                out["greedy_rps"] = {"off": round(g_off, 1),
                                     "on": round(g_on, 1)}
                out["polite_p99_s"] = {"off": round(p99_off, 3),
                                       "on": round(p99_on, 3)}
                assert g_on < g_off * 0.7, \
                    f"flood not shaped: {g_off:.0f} -> {g_on:.0f}/s"
                assert p99_on < 2.0, \
                    f"polite p99 unbounded under flood: {p99_on:.2f}s"
                assert retries["polite"] <= 2, \
                    f"polite writer kept erroring: {retries}"
                out["reload_retries"] = dict(retries)
                assert greedy.graph and any(
                    l.qos_backoff_total > 0 for l in walk(greedy.graph.top)
                    if hasattr(l, "qos_backoff_total")), \
                    "greedy client never paid a backoff"

                # the shaping is visible in volume status clients
                async with MgmtClient(st.d.host, st.d.port) as c:
                    deep = await c.call("volume-status-deep",
                                        name=st.name, what="clients")
                rows = [r for b in deep["bricks"].values()
                        for r in b.get("clients", [])]
                shed = sum(r.get("qos", {}).get("shed_fops", 0)
                           for r in rows)
                assert shed > 0, "no brick reported qos sheds"
                out["status_shed_fops"] = shed

                # ...and in the event plane: transition-edge THROTTLE
                starts = [e for e in ev.recent
                          if e.get("event") == "THROTTLE_START"]
                assert starts, "no THROTTLE_START reached eventsd"
                assert all(e.get("reason") == "rate" for e in starts)
                out["throttle_starts"] = len(starts)
            finally:
                await greedy.unmount()
                await polite.unmount()
    finally:
        os.environ.pop("GFTPU_EVENTSD", None)
        gf_events.configure(None)
        await ev.stop()
    return out


@scenario("rebalance_grow")
async def rebalance_grow(base: str, opts) -> dict:
    """ISSUE 11 acceptance: grow a LOADED disperse 4+2 volume by an
    added distribute leg while it serves — fix-layout + daemon
    migration under live reads/writes, a SIGKILL + respawn mid-run
    RESUMES from the checkpoint (never restarts the walk), serving
    read latency stays bounded throughout, and every pre-existing and
    in-flight object is byte-identical after convergence."""
    out: dict = {}
    async with Stack(base) as st:
        await st.set("cluster.rebal-throttle", "lazy")
        await st.set("rebalance.checkpoint-interval", "0.1")
        cl = await st.mount()
        try:
            # pre-existing namespace spread over directories, so the
            # checkpoint has directory boundaries to land on
            pre: dict[str, bytes] = {}
            for dd in range(6):
                await cl.mkdir(f"/d{dd}")
                for i in range(6):
                    p = f"/d{dd}/f{i}"
                    pre[p] = payload_for(dd * 16 + i)[:256 * 1024]
                    await cl.write_file(p, pre[p])
            # serving load: reads with latency recorded (bounded!),
            # plus in-flight writes landing under the NEW layout
            lat: list[float] = []
            inflight: dict[str, bytes] = {}
            retries = {"n": 0}
            stop_load = asyncio.Event()

            async def load():
                i = 0
                names = list(pre)
                while not stop_load.is_set():
                    p = names[i % len(names)]
                    t0 = time.monotonic()
                    try:
                        got = await asyncio.wait_for(cl.read_file(p), 60)
                    except FopError:
                        # one bounded retry: the live add-brick graph
                        # swap can catch a read mid-flight
                        retries["n"] += 1
                        got = await asyncio.wait_for(cl.read_file(p), 60)
                    lat.append(time.monotonic() - t0)
                    assert bytes(got) == pre[p], \
                        f"serving read of {p} returned wrong bytes"
                    if i % 3 == 0:
                        np_path = f"/d{i % 6}/new{i}"
                        body = payload_for(7000 + i)[:64 * 1024]
                        try:
                            await asyncio.wait_for(
                                cl.write_file(np_path, body), 60)
                        except FopError:
                            # same graph-swap blip as the read above
                            # (EEXIST from a landed first try falls
                            # back to open+write inside write_file)
                            retries["n"] += 1
                            await asyncio.wait_for(
                                cl.write_file(np_path, body), 60)
                        inflight[np_path] = body
                    i += 1
                    await asyncio.sleep(0.05)

            loader = asyncio.ensure_future(load())
            try:
                async with MgmtClient(st.d.host, st.d.port) as c:
                    # a second 4+2 leg: the volume becomes 2x(4+2)
                    await c.call("volume-add-brick", name=st.name,
                                 bricks=[{"path": os.path.join(
                                     base, f"nb{i}")} for i in range(N)])
                    await c.call("volume-rebalance", name=st.name,
                                 action="start")

                def rb() -> dict:
                    return st.d._vol(st.name).get("rebalance") or {}

                # wait for a mid-migration checkpoint, then SIGKILL
                deadline = time.monotonic() + 150
                while True:
                    r = rb()
                    ck = r.get("checkpoint") or {}
                    if r.get("phase") == "migrate" and \
                            ck.get("last_dir") and \
                            (r.get("counters") or {}).get("moved", 0):
                        break
                    assert r.get("status") == "started", \
                        f"rebalance died or finished too fast: {r}"
                    assert time.monotonic() < deadline, r
                    await asyncio.sleep(0.05)
                pre_ctr = dict(rb()["counters"])
                proc = st.d.rebalanced[st.name]
                os.kill(proc.pid, signal.SIGKILL)
                await asyncio.to_thread(proc.wait)
                out["killed_at_checkpoint"] = \
                    rb()["checkpoint"]["last_dir"]
                async with MgmtClient(st.d.host, st.d.port) as c:
                    resp = await c.call("volume-rebalance",
                                        name=st.name, action="start")
                assert resp["status"] == "resumed", resp
                deadline = time.monotonic() + 240
                while rb().get("status") not in ("completed", "failed"):
                    assert time.monotonic() < deadline, rb()
                    await asyncio.sleep(0.3)
                r = rb()
                assert r["status"] == "completed", r
                assert r.get("resumed_from", {}).get("last_dir"), \
                    f"respawn restarted instead of resuming: {r}"
                fin = r["counters"]
                assert fin["scanned"] > pre_ctr["scanned"], (pre_ctr, fin)
                assert fin["dirs_fixed"] == pre_ctr["dirs_fixed"], \
                    "respawn redid fix-layout"
                out["resumed_from"] = r["resumed_from"]
                out["migrated"] = {"moved": fin["moved"],
                                   "bytes": fin["bytes_moved"],
                                   "failed": fin["failed"]}
                assert fin["failed"] == 0, fin
            finally:
                stop_load.set()
                await loader
            assert lat, "serving load never ran"
            p99 = sorted(lat)[int(0.99 * (len(lat) - 1))]
            out["serving_reads"] = len(lat)
            out["read_retries"] = retries["n"]
            out["read_p99_s"] = round(p99, 2)
            assert p99 < 30, \
                f"serving latency unbounded during rebalance: {p99:.1f}s"
        finally:
            await cl.unmount()
        # fresh mount: every object byte-identical after convergence
        cl2 = await st.mount()
        try:
            for p, body in {**pre, **inflight}.items():
                got = await asyncio.wait_for(cl2.read_file(p), 60)
                assert bytes(got) == body, \
                    f"{p} not byte-identical after growth"
            out["objects_verified"] = len(pre) + len(inflight)
        finally:
            await cl2.unmount()
    return out


@scenario("fuse")
async def fuse(base: str, opts) -> dict:
    """Kernel-mount responsiveness through a brick kill (gated behind
    --with-fuse: /dev/fuse behavior is kernel-dependent in sandboxes)."""
    if not opts.with_fuse:
        return {"skipped": "pass --with-fuse to run (kernel-dependent)"}
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "..", "tests"))
    from harness import spawn_fuse, stop_fuse

    out: dict = {}
    async with Stack(base) as st:
        mnt = os.path.join(base, "mnt")
        os.makedirs(mnt)
        proc = spawn_fuse(f"127.0.0.1:{st.d.port}", st.name,
                          os.path.join(base, "ready"), mnt)
        try:
            pay = payload_for(600)

            def timed(fn, seconds, label):
                box: dict = {}

                def work():
                    try:
                        box["v"] = fn()
                    except BaseException as e:  # noqa: BLE001
                        box["e"] = e

                th = threading.Thread(target=work, daemon=True)
                th.start()
                th.join(seconds)
                if th.is_alive():
                    raise TimeoutError(f"fuse {label} hung")
                if "e" in box:
                    raise box["e"]
                return box.get("v")

            timed(lambda: open(os.path.join(mnt, "f"), "wb").write(pay),
                  120, "write")
            port = st.kill_brick(4)
            got = timed(lambda: open(os.path.join(mnt, "f"),
                                     "rb").read(), 120, "degraded read")
            assert got == pay, "fuse degraded read parity"
            out["fuse_degraded_read_ok"] = True
            await st.restart_brick(4, port)
        finally:
            stop_fuse(proc, mnt)
    return out


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


async def warmup(base: str) -> None:
    """Spin every process-wide lazy pool (client event pool, codec
    probe, wirec build) BEFORE the leak baseline: those threads are
    by-design persistent, not leaks."""
    async with Stack(os.path.join(base, "warm"), name="warm") as st:
        cl = await st.mount()
        try:
            pay = payload_for(0)
            await cl.write_file("/w", pay)
            assert bytes(await cl.read_file("/w")) == pay
        finally:
            await cl.unmount()


def live_threads() -> set:
    return {t.name for t in threading.enumerate() if t.is_alive()}


async def settle_tasks(grace: float = 3.0) -> list:
    """Let teardown finish, then report still-pending tasks (excluding
    the runner itself)."""
    me = asyncio.current_task()
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        rest = [t for t in asyncio.all_tasks() if t is not me]
        if not rest:
            return []
        await asyncio.sleep(0.2)
    return [repr(t)[:120] for t in asyncio.all_tasks() if t is not me]


async def amain(opts) -> dict:
    names = opts.scenario or [n for n in SCENARIOS if n != "fuse"]
    if opts.with_fuse and "fuse" not in names:
        names.append("fuse")
    for n in names:
        if n not in SCENARIOS:
            raise SystemExit(f"unknown scenario {n!r} "
                             f"(have: {', '.join(SCENARIOS)})")
    root = tempfile.mkdtemp(prefix="gftpu-chaos")
    report: dict = {"ok": True, "scenarios": {},
                    "host_cores": len(os.sched_getaffinity(0))}
    try:
        await warmup(root)
        baseline_threads = live_threads()
        for name in names:
            base = os.path.join(root, name)
            os.makedirs(base, exist_ok=True)
            t0 = time.monotonic()
            try:
                detail = await asyncio.wait_for(
                    SCENARIOS[name](base, opts), SCENARIO_DEADLINE_S)
                detail["ok"] = True
            except BaseException as e:  # noqa: BLE001 - report, don't die
                detail = {"ok": False, "error": repr(e)[:300],
                          "trace": traceback.format_exc()[-1200:]}
                report["ok"] = False
            detail["elapsed_s"] = round(time.monotonic() - t0, 1)
            report["scenarios"][name] = detail
            print(f"[chaos] {name}: "
                  f"{'ok' if detail['ok'] else 'FAIL'} "
                  f"({detail['elapsed_s']}s)", file=sys.stderr)
        # leak audit: nothing the failure paths spun up may survive
        leaked_tasks = await settle_tasks()
        # codec/executor threads shut down asynchronously: poll out
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            leaked = sorted(live_threads() - baseline_threads)
            if not leaked:
                break
            await asyncio.sleep(0.3)
        report["leaked_threads"] = leaked
        report["leaked_tasks"] = leaked_tasks
        if leaked or leaked_tasks:
            report["ok"] = False
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return report


def compare_reports(now: dict, prev: dict) -> list[dict]:
    """Baseline-compare (ISSUE 20): judge this run's timing rows
    against a previous ``--json`` report.  Chaos rows are WALL-CLOCK
    TIMES, so the gate is the mirror of bench.py's throughput gate: a
    regression is a time that GREW beyond the documented 2-core swing
    band (bench.SWING_BAND_WIRE — identical-config full-stack rows
    swing 4.65x on the shared host; docs/observability.md).  Only
    scenarios that PASSED in both runs are comparable; every flag is
    machine-readable: {"row", "prev", "now", "grow_pct", "band"}."""
    import bench

    band = bench.SWING_BAND_WIRE
    flags: list[dict] = []

    def check(name: str, new, old) -> None:
        if isinstance(new, (int, float)) and isinstance(old, (int, float)) \
                and old > 0 and new > old * band:
            flags.append({"row": name, "prev": old, "now": new,
                          "grow_pct": round(100 * (new / old - 1), 1),
                          "band": round(band, 2)})

    for name, d in (now.get("scenarios") or {}).items():
        pd = (prev.get("scenarios") or {}).get(name)
        if not isinstance(pd, dict) or not (d.get("ok") and pd.get("ok")):
            continue  # a failed run's timings are not a baseline
        for k, v in d.items():
            if k.endswith("_s"):
                check(f"{name}.{k}", v, pd.get(k))
    return flags


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--scenario", action="append",
                   help="scenario name (repeatable); default = all "
                        "except fuse")
    p.add_argument("--json", action="store_true")
    p.add_argument("--with-fuse", action="store_true",
                   help="include the kernel-mount scenario")
    p.add_argument("--baseline",
                   help="previous --json report to judge this run's "
                        "timing rows against (2-core swing band)")
    opts = p.parse_args()
    report = asyncio.run(amain(opts))
    if opts.baseline:
        try:
            with open(opts.baseline) as f:
                report["regressions"] = compare_reports(report,
                                                        json.load(f))
        except (OSError, ValueError) as e:
            report["regressions"] = [{"row": "baseline-unreadable",
                                      "error": repr(e)[:200]}]
    if opts.json:
        print(json.dumps(report, indent=1, default=repr))
    else:
        for name, d in report["scenarios"].items():
            print(f"{name}: {'ok' if d.get('ok') else 'FAIL'}  {d}")
        print(f"leaked_threads={report['leaked_threads']} "
              f"leaked_tasks={len(report['leaked_tasks'])}")
        for r in report.get("regressions", []):
            print(f"regression: {r}")
        print("chaos:", "GREEN" if report["ok"] else "RED")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
