#!/usr/bin/env bash
# ci.sh — the one-command pre-merge gate (ISSUE 3 satellite; the
# regression signal ROADMAP's tier-1 bar depends on):
#
#   0. graft-lint               tools/graft_lint cross-file invariant
#                               suite (fop/option/async/errno/metrics
#                               planes, ISSUE 13) — runs FIRST because
#                               it is the cheapest signal (<30s);
#                               --json archived to /tmp/gftpu-ci
#   1. tools/flake_gate.sh      tier-1 twice, diffing the failure sets
#                               (stable failures -> exit 1, flakes -> 2)
#   2. bench contract test      the driver-facing reporting contract
#                               (compact parseable headline + detail
#                               file) — a broken emit() loses a whole
#                               round's record, so it gates merges even
#                               though the full bench doesn't
#   3. metrics smoke            start a 1-brick volume, drive two fops,
#                               scrape the unified registry and assert
#                               the required families are present and
#                               monotonic (ISSUE 4: a silently-empty
#                               metrics dump must not merge)
#   4. gateway smoke            serve a managed 1-brick volume through
#                               the HTTP object gateway: PUT/GET/
#                               ranged-GET/DELETE/list over real HTTP,
#                               gateway registry families asserted, and
#                               the glusterd-spawned daemon lifecycle
#                               (`volume gateway start|status|stop`)
#                               exercised end to end (ISSUE 6)
#   5. concurrency smoke        1-brick volume served with
#                               server.event-threads=4: interleaved
#                               pipelined writes from one connection
#                               dispatch in order and read back
#                               byte-identical, a second connection
#                               proceeds in parallel, the
#                               gftpu_event_threads* families are
#                               present and moving, and the managed
#                               op-version-9 volume-set path applies
#                               the key to a live brick (ISSUE 7)
#   6. mesh smoke               the mesh-codec data plane under 8
#                               forced host devices: the parity +
#                               routing tests of test_mesh_plane.py,
#                               then a batched encode through a
#                               mesh-armed BatchingCodec asserting the
#                               gftpu_mesh_launches_total family
#                               appears with origin=serve (ISSUE 8)
#   7. chaos smoke              ONE bounded failure-containment
#                               scenario (tools/chaos.py
#                               degraded_read): brick SIGKILL
#                               mid-write -> degraded reads
#                               byte-identical -> restart -> heal
#                               converges -> the healed brick serves,
#                               with the zero-leak audit (ISSUE 9)
#   8. delta-write smoke        managed systematic-by-default volume
#                               serves an unaligned write via the
#                               parity-delta path (ISSUE 10)
#   9. rebalance smoke          add-brick + managed rebalance daemon
#                               converges, task row + families,
#                               bytes exact (ISSUE 11)
#  10. process-plane smoke      workers=2 managed gateway pool:
#                               byte-exact PUT/GET through the
#                               shared-nothing workers, worker
#                               SIGKILL respawns and keeps serving
#                               (ISSUE 12)
#  11. lease smoke              hot GETs off the lease-held gateway
#                               object cache at zero wire fops,
#                               recall-exact coherence, cache/lease
#                               families, v15 keys (ISSUE 16)
#  12. qos smoke                per-client admission shed at a tight
#                               fops cap on both wire paths,
#                               gftpu_qos_* family monotonicity, live
#                               v16 volume-set flip, shaping column in
#                               volume-status-deep (ISSUE 17)
#  13. shm smoke                same-host bulk lane arms against a
#                               managed brick, shm families move both
#                               directions, live volume-set off
#                               downgrades inline (ISSUE 18)
#  14. incident smoke           managed volume with
#                               diagnostics.incident-dir armed: brick
#                               SIGKILL auto-captures a local bundle,
#                               `volume incident list` shows it,
#                               `show` round-trips the JSON (ISSUE 19)
#  15. alert smoke              managed volume with a v19 error-ratio
#                               SLO rule: an error-gen readv storm
#                               raises the alert in `volume alerts`,
#                               ALERT_RAISED rides real UDP eventsd and
#                               auto-captures an incident bundle whose
#                               history section shows the error ramp;
#                               healthy traffic clears it and the
#                               CLEARED edge lands in alert history
#                               (ISSUE 20)
#
# Usage:  tools/ci.sh [extra pytest args for the tier-1 runs...]
# Exit: first failing stage's code; 0 = mergeable.

set -u
cd "$(dirname "$0")/.."

echo "== ci: stage 0 — graft-lint (cross-file invariants) =="
mkdir -p /tmp/gftpu-ci
timeout -k 5 60 env JAX_PLATFORMS=cpu \
    python tools/graft_lint/run.py --json \
    > /tmp/gftpu-ci/graft_lint.json
lint_rc=$?
if [ $lint_rc -ne 0 ]; then
    echo "ci: graft-lint findings (archived at"
    echo "    /tmp/gftpu-ci/graft_lint.json) — not mergeable"
    python - <<'PYEOF'
import json
try:
    d = json.load(open("/tmp/gftpu-ci/graft_lint.json"))
except Exception as e:  # internal error/timeout: archive is not JSON
    print(f"  (no findings archive — linter internal error or "
          f"timeout: {e})")
else:
    for f in d.get("findings", []):
        print(f"  {f['path']}:{f['line']}: {f['code']} {f['message']}")
PYEOF
    exit $lint_rc
fi
python - <<'PYEOF'
import json
d = json.load(open("/tmp/gftpu-ci/graft_lint.json"))
per = d.get("checker_seconds", {})
slow = sorted(per.items(), key=lambda kv: -kv[1])[:3]
pretty = ", ".join(f"{k} {v:.1f}s" for k, v in slow)
print(f"ci: lint clean ({d['seconds']}s of a 30s budget; "
      f"slowest: {pretty}; archived with per-checker timings)")
PYEOF

echo "== ci: flake gate (tier-1 x2) =="
tools/flake_gate.sh "$@"
gate_rc=$?
if [ $gate_rc -eq 1 ]; then
    echo "ci: STABLE tier-1 failures — not mergeable"
    exit 1
fi

echo "== ci: bench reporting contract =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_bench_contract.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
bench_rc=$?
if [ $bench_rc -ne 0 ]; then
    echo "ci: bench contract broken — not mergeable"
    exit $bench_rc
fi

echo "== ci: metrics smoke (1-brick volume, scrape + monotonicity,"
echo "       status clients + eventsapi) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, os, tempfile

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.daemon import serve_brick

BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume locks
    type features/locks
    subvolumes posix
end-volume
volume stats
    type debug/io-stats
    subvolumes locks
end-volume
"""
CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume stats
end-volume
"""
REQUIRED = ("gftpu_wire_blob_stats",
            "gftpu_decode_program_cache_events_total",
            "gftpu_codec_device_probe",
            "gftpu_slow_fops_total")

def tx_bytes(snap):
    return sum(v for l, v in snap["gftpu_wire_blob_stats"]["samples"]
               if l.get("counter") == "tx_bytes")

async def main():
    base = tempfile.mkdtemp(prefix="metrics-smoke")
    server = await serve_brick(BRICK.format(dir=os.path.join(base, "b")))
    g = Graph.construct(CLIENT.format(port=server.port))
    c = Client(g)
    await c.mount()
    for _ in range(200):
        if g.top.connected:
            break
        await asyncio.sleep(0.05)
    assert g.top.connected, "client never connected"
    snap0 = REGISTRY.snapshot()
    for fam in REQUIRED:
        assert fam in snap0, f"missing metrics family {fam}"
    await c.write_file("/smoke", b"m" * 65536)      # fop 1
    assert await c.read_file("/smoke") == b"m" * 65536  # fop 2
    snap1 = REGISTRY.snapshot()
    assert tx_bytes(snap1) > tx_bytes(snap0), \
        "wire blob counters not monotonic across fops"
    rpc = await g.top.remote("metrics_dump")
    assert "gftpu_wire_blob_stats" in rpc, "metrics_dump RPC empty"
    text = REGISTRY.render()
    assert "# TYPE gftpu_wire_blob_stats counter" in text
    # per-client accounting rode the same fops (ISSUE 5): the brick
    # names this client and its byte counters moved
    st = await g.top._call("__status__", ("clients",), {})
    rows = [r for r in st["clients"] if not r["mgmt"]]
    assert rows and rows[0]["bytes_rx"] >= 65536, \
        "client accounting row missing or empty"
    await c.unmount()
    await server.stop()

    # -- managed path: glusterd volume + eventsd (ISSUE 5) --------------
    from glusterfs_tpu.mgmt.eventsd import EventsDaemon
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    ed = EventsDaemon()
    udp, ctl = await ed.start()
    os.environ["GFTPU_EVENTSD"] = f"127.0.0.1:{udp}"
    os.environ["GFTPU_EVENTSD_CTL"] = f"127.0.0.1:{ctl}"
    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as mc:
            await mc.call("volume-create", name="smoke",
                          vtype="distribute",
                          bricks=[{"path": os.path.join(base, "vb0")}])
            await mc.call("volume-start", name="smoke")
        m = await mount_volume(d.host, d.port, "smoke")
        try:
            await m.write_file("/s", b"s" * 65536)
            st = await d.op_volume_status_deep("smoke", "clients")
            assert "partial" not in st, st
            rows = [r for r in
                    st["bricks"]["smoke-brick-0"]["clients"]
                    if not r["mgmt"]]
            assert rows and rows[0]["bytes_rx"] >= 65536, \
                f"volume status clients: no accounted client row: {st}"
            ev = await d.op_eventsapi("status")
            assert ev["nodes"], "eventsapi status empty"
            ok = False
            for _ in range(50):
                recent = (await d.op_eventsapi_local("recent"))["events"]
                if any(e.get("event") == "CLIENT_CONNECT"
                       for e in recent):
                    ok = True
                    break
                await asyncio.sleep(0.1)
            assert ok, "no CLIENT_CONNECT in eventsd history"
        finally:
            await m.unmount()
    finally:
        await d.stop()
        await ed.stop()
    print("metrics smoke: families present, counters monotonic, "
          "client accounting + CLIENT_CONNECT event observed")

asyncio.run(main())
EOF
smoke_rc=$?
if [ $smoke_rc -ne 0 ]; then
    echo "ci: metrics smoke failed — not mergeable"
    exit $smoke_rc
fi

echo "== ci: gateway smoke (managed volume, real HTTP, registry"
echo "       families, spawned-daemon lifecycle) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, json, os, tempfile

from glusterfs_tpu.api.glfs import Client, wait_connected
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.gateway import ClientPool, ObjectGateway
from glusterfs_tpu.gateway.minihttp import fetch as http
from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

async def main():
    base = tempfile.mkdtemp(prefix="gw-smoke")
    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as c:
            await c.call("volume-create", name="gwv",
                         vtype="distribute",
                         bricks=[{"path": os.path.join(base, "b0")}])
            await c.call("volume-start", name="gwv")
            spec = await c.call("getspec", name="gwv")

        # in-process gateway over the managed volfile: the dialect +
        # the registry families live in THIS process for asserting
        async def factory():
            g = Graph.construct(spec["volfile"])
            cl = Client(g)
            await cl.mount()
            await wait_connected(g)
            return cl

        gw = ObjectGateway(ClientPool(factory, 2), volume="gwv")
        await gw.start()
        H, P = gw.host, gw.port
        payload = bytes(range(256)) * 256  # 64 KiB
        st, _, _ = await http(H, P, "PUT", "/bkt")
        assert st == 200, st
        st, hd, _ = await http(H, P, "PUT", "/bkt/dir/obj",
                               body=payload)
        assert st == 200 and hd.get("etag"), (st, hd)
        st, _, data = await http(H, P, "GET", "/bkt/dir/obj")
        assert st == 200 and data == payload
        st, hd, data = await http(H, P, "GET", "/bkt/dir/obj",
                                  headers={"range": "bytes=100-4099"})
        assert st == 206 and data == payload[100:4100], st
        assert hd["content-range"] == f"bytes 100-4099/{len(payload)}"
        st, _, data = await http(H, P, "GET", "/bkt?list&delimiter=/")
        out = json.loads(data)
        assert st == 200 and out["common_prefixes"] == ["dir/"], out
        st, _, _ = await http(H, P, "DELETE", "/bkt/dir/obj")
        assert st == 204, st
        st, _, _ = await http(H, P, "GET", "/bkt/dir/obj")
        assert st == 404, st
        snap = REGISTRY.snapshot()
        for fam in ("gftpu_gateway_requests_total",
                    "gftpu_gateway_request_seconds",
                    "gftpu_gateway_inflight",
                    "gftpu_gateway_bytes_total",
                    "gftpu_gateway_body_writes_total",
                    "gftpu_gateway_throttled_total",
                    "gftpu_gateway_events_total"):
            assert fam in snap, f"missing gateway family {fam}"
        reqs = {(s[0]["method"], s[0]["status"]): s[1] for s in
                snap["gftpu_gateway_requests_total"]["samples"]}
        assert reqs[("GET", "200")] >= 1 and reqs[("PUT", "200")] >= 2
        await gw.stop()

        # spawned-daemon lifecycle: volume gateway start -> HTTP ->
        # status -> stop (the CLI path, sans argparse)
        st = await d.op_volume_gateway("gwv", "start")
        port = 0
        for _ in range(600):
            st = await d.op_volume_gateway("gwv", "status")
            if st["gateway"]["online"] and st["gateway"]["port"]:
                port = st["gateway"]["port"]
                break
            await asyncio.sleep(0.1)
        assert port, f"spawned gateway never came up: {st}"
        s = 0
        for _ in range(100):
            try:
                s, _, _ = await http("127.0.0.1", port, "PUT", "/lb")
                if s == 200:
                    break
            except (ConnectionError, OSError):
                pass
            await asyncio.sleep(0.1)
        assert s == 200, f"spawned gateway unreachable (last: {s})"
        s, _, _ = await http("127.0.0.1", port, "PUT", "/lb/k",
                             body=b"spawned")
        assert s == 200
        s, _, data = await http("127.0.0.1", port, "GET", "/lb/k")
        assert s == 200 and data == b"spawned"
        await d.op_volume_gateway("gwv", "stop")
        for _ in range(100):
            st = await d.op_volume_gateway("gwv", "status")
            if not st["gateway"]["online"]:
                break
            await asyncio.sleep(0.1)
        assert not st["gateway"]["online"], st
    finally:
        await d.stop()
    print("gateway smoke: dialect + ranged GET + listing over real "
          "HTTP, families present, spawned lifecycle green")

asyncio.run(main())
EOF
gw_rc=$?
if [ $gw_rc -ne 0 ]; then
    echo "ci: gateway smoke failed — not mergeable"
    exit $gw_rc
fi

echo "== ci: concurrency smoke (event-threads=4, interleaved clients,"
echo "       ordering + gftpu_event_threads families) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, os, tempfile

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc, walk
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.storage.posix import PosixLayer

BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume locks
    type features/locks
    subvolumes posix
end-volume
volume srv
    type protocol/server
    option event-threads 4
    subvolumes locks
end-volume
"""
CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume srv
    option event-threads 2
end-volume
"""

async def connect(port):
    g = Graph.construct(CLIENT.format(port=port))
    c = Client(g)
    await c.mount()
    for _ in range(200):
        if g.top.connected:
            break
        await asyncio.sleep(0.05)
    assert g.top.connected, "client never connected"
    return c, g.top

async def main():
    base = tempfile.mkdtemp(prefix="evt-smoke")
    server = await serve_brick(BRICK.format(dir=os.path.join(base, "b")))
    assert server.event_pool().size == 4, server.event_pool().size
    c1, cl1 = await connect(server.port)
    c2, cl2 = await connect(server.port)

    # ordering: 16 pipelined 8KiB writes from ONE connection must
    # enter the brick graph in send order through the 4-thread plane
    arrivals = []
    real = PosixLayer.writev
    async def recording(self, fd, data, offset, *a, **kw):
        arrivals.append(offset)
        return await real(self, fd, data, offset, *a, **kw)
    chunk = 8192
    fd, _ = await cl1.create(Loc("/ord"), os.O_CREAT | os.O_RDWR, 0o644)
    PosixLayer.writev = recording
    try:
        await asyncio.gather(*(
            asyncio.ensure_future(
                cl1.writev(fd, bytes([i]) * chunk, i * chunk))
            for i in range(16)))
    finally:
        PosixLayer.writev = real
    assert arrivals == [i * chunk for i in range(16)], \
        f"dispatch reordered: {arrivals}"
    # interleaved second connection, byte identity on both
    await asyncio.gather(
        c1.write_file("/a", b"a" * 65536),
        c2.write_file("/b", b"b" * 65536))
    assert await c2.read_file("/a") == b"a" * 65536
    assert await c1.read_file("/b") == b"b" * 65536
    assert await c1.read_file("/ord") == b"".join(
        bytes([i]) * chunk for i in range(16))

    snap = REGISTRY.snapshot()
    for fam in ("gftpu_event_threads", "gftpu_event_threads_busy",
                "gftpu_event_frames_total"):
        assert fam in snap, f"missing family {fam}"
    pools = {s[0]["pool"]: s[1]
             for s in snap["gftpu_event_threads"]["samples"]}
    assert pools.get("srv") == 4, pools
    turned = sum(s[1] for s in
                 snap["gftpu_event_frames_total"]["samples"]
                 if s[0]["pool"] == "srv")
    assert turned > 0, "no frames turned on the brick pool"
    await c1.unmount()
    await c2.unmount()
    await server.stop()

    # managed path: the op-version-9 key reaches a live brick
    # subprocess through `volume set` (glusterd gating + volgen map +
    # live reconfigure)
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as mc:
            await mc.call("volume-create", name="evt",
                          vtype="distribute",
                          bricks=[{"path": os.path.join(base, "vb0")}])
            await mc.call("volume-start", name="evt")
            await mc.call("volume-set", name="evt",
                          key="server.event-threads", value="4")
            await mc.call("volume-set", name="evt",
                          key="client.event-threads", value="2")
        m = await mount_volume(d.host, d.port, "evt")
        try:
            await m.write_file("/s", b"s" * 65536)
            assert await m.read_file("/s") == b"s" * 65536
            g = m.graph
            cl = next(l for l in walk(g.top)
                      if l.type_name == "protocol/client")
            rpc = await cl._call("metrics_dump", (), {})
            pools = {s[0]["pool"]: s[1] for s in
                     rpc["gftpu_event_threads"]["samples"]}
            assert any(v == 4 for v in pools.values()), \
                f"brick pool not resized by volume set: {pools}"
        finally:
            await m.unmount()
    finally:
        await d.stop()
    print("concurrency smoke: ordering held through 4 frame turners, "
          "interleaved clients byte-identical, families present, "
          "managed volume-set applied event-threads=4 live")

asyncio.run(main())
EOF
evt_rc=$?
if [ $evt_rc -ne 0 ]; then
    echo "ci: concurrency smoke failed — not mergeable"
    exit $evt_rc
fi

echo "== ci: mesh smoke (parity + routing on 8 forced host devices,"
echo "       gftpu_mesh_launches_total after a batched encode) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_mesh_plane.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly
mesh_rc=$?
if [ $mesh_rc -ne 0 ]; then
    echo "ci: mesh parity/routing tests failed — not mergeable"
    exit $mesh_rc
fi
timeout -k 10 180 env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" python - <<'EOF'
import asyncio, numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.ops import gf256
from glusterfs_tpu.ops.batch import BatchingCodec

async def main():
    codec = BatchingCodec(4, 2, "ref", mesh=True, min_batch=0,
                          window=0.005)
    assert await codec.ensure_mesh(), codec._mesh_state
    datas = [np.random.default_rng(i).integers(0, 256, 4 * 512 * 4,
                                               dtype=np.uint8)
             for i in range(6)]
    outs = await asyncio.gather(*(codec.encode_async(d) for d in datas))
    for d, o in zip(datas, outs):
        assert np.array_equal(o, gf256.ref_encode(d, 4, 6)), "parity"
    snap = REGISTRY.snapshot()
    fam = snap.get("gftpu_mesh_launches_total")
    assert fam, "gftpu_mesh_launches_total family missing"
    serve = [s for s in fam["samples"]
             if s[0].get("op") == "encode"
             and s[0].get("origin") == "serve"]
    assert serve and serve[0][1] >= 1, fam["samples"]
    assert codec.max_batch == 6, codec.max_batch
    devs = {s[0]["axis"]: s[1]
            for s in snap["gftpu_mesh_devices"]["samples"]}
    assert devs.get("total") == 8, devs
    codec.close()
    print("mesh smoke: 6 concurrent encodes coalesced onto the "
          "(dp, frag) mesh, launches family present, parity held")

asyncio.run(main())
EOF
mesh_rc=$?
if [ $mesh_rc -ne 0 ]; then
    echo "ci: mesh smoke failed — not mergeable"
    exit $mesh_rc
fi

echo "== ci: chaos smoke (brick kill -> degraded read parity ->"
echo "       restart -> heal converges; zero-leak audit) =="
timeout -k 10 420 env JAX_PLATFORMS=cpu \
    python tools/chaos.py --scenario degraded_read --json
chaos_rc=$?
if [ $chaos_rc -ne 0 ]; then
    echo "ci: chaos smoke failed — not mergeable"
    exit $chaos_rc
fi

echo "== ci: delta-write smoke (managed systematic volume, unaligned"
echo "       write -> gftpu_ec_delta_writes_total monotonicity) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, os, shutil, tempfile

async def main():
    from glusterfs_tpu.core.layer import walk
    from glusterfs_tpu.core.metrics import REGISTRY
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    base = tempfile.mkdtemp(prefix="ci-delta")
    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as c:
            await c.call("volume-create", name="dv", vtype="disperse",
                         redundancy=2,
                         bricks=[{"path": os.path.join(base, f"b{i}")}
                                 for i in range(6)])
            info = await c.call("volume-info", name="dv")
            assert info["dv"].get("systematic") == 1, \
                "disperse create did not default systematic at op12"
            await c.call("volume-start", name="dv")
        cl = await mount_volume(d.host, d.port, "dv")
        try:
            ec = next(l for l in walk(cl.graph.top)
                      if l.type_name == "cluster/disperse")
            data = bytes(range(256)) * 32  # 8 KiB = 4 stripes at 4+2
            await cl.write_file("/f", data)

            def fam(name):
                snap = REGISTRY.snapshot()
                return sum(s[1] for s in snap[name]["samples"]
                           if s[0].get("layer") == ec.name)

            d0 = fam("gftpu_ec_delta_writes_total")
            f = await cl.open("/f")
            await f.write(b"Q" * 700, 1000)  # sub-stripe, inside size
            await f.close()
            d1 = fam("gftpu_ec_delta_writes_total")
            assert d1 == d0 + 1, (d0, d1)
            saved = fam("gftpu_ec_delta_bytes_saved_total")
            assert saved > 0, "delta path saved nothing?"
            exp = bytearray(data); exp[1000:1700] = b"Q" * 700
            got = await cl.read_file("/f")
            assert bytes(got) == bytes(exp), "delta smoke parity"
        finally:
            await cl.unmount()
    finally:
        await d.stop()
        shutil.rmtree(base, ignore_errors=True)
    print("delta smoke: managed systematic-by-default volume served an "
          "unaligned write via the parity-delta path (family +1, "
          "bytes-saved > 0, bytes exact)")

asyncio.run(main())
EOF
delta_rc=$?
if [ $delta_rc -ne 0 ]; then
    echo "ci: delta-write smoke failed — not mergeable"
    exit $delta_rc
fi

echo "== ci: rebalance smoke (managed volume, add-brick, daemon"
echo "       start -> status converges, families present) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, json, os, shutil, tempfile, time

async def main():
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    base = tempfile.mkdtemp(prefix="ci-rebal")
    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as c:
            await c.call("volume-create", name="rv", vtype="distribute",
                         redundancy=0,
                         bricks=[{"path": os.path.join(base, f"b{i}")}
                                 for i in range(2)])
            await c.call("volume-start", name="rv")
        cl = await mount_volume(d.host, d.port, "rv")
        data = {}
        try:
            for dd in range(3):
                await cl.mkdir(f"/d{dd}")
                for i in range(5):
                    p = f"/d{dd}/f{i}"
                    data[p] = f"{p}-payload".encode() * 150
                    await cl.write_file(p, data[p])
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-add-brick", name="rv",
                             bricks=[{"path": os.path.join(base, "b2")}])
                out = await c.call("volume-rebalance", name="rv",
                                   action="start")
                assert out["status"] == "started", out
                deadline = time.monotonic() + 240
                while True:
                    st = await c.call("volume-rebalance", name="rv",
                                      action="status")
                    rb = st["rebalance"]
                    if rb.get("status") in ("completed", "failed"):
                        break
                    assert time.monotonic() < deadline, rb
                    await asyncio.sleep(0.3)
                assert rb["status"] == "completed", rb
                ctr = rb["counters"]
                assert ctr["moved"] >= 1 and ctr["failed"] == 0, ctr
                assert ctr["scanned"] == ctr["moved"] + ctr["skipped"], ctr
                vs = await c.call("volume-status", name="rv")
                kinds = [t["type"] for t in vs.get("tasks", [])]
                assert "rebalance" in kinds, vs.get("tasks")
            with open(os.path.join(d.workdir,
                                   "rebalanced-rv.json")) as f:
                fams = json.load(f)["families"]
            for fam in ("gftpu_rebalance_files_total",
                        "gftpu_rebalance_bytes_total",
                        "gftpu_rebalance_failures_total",
                        "gftpu_rebalance_phase"):
                assert fam in fams, fam
            for p, body in data.items():
                assert bytes(await cl.read_file(p)) == body, p
        finally:
            await cl.unmount()
    finally:
        await d.stop()
        shutil.rmtree(base, ignore_errors=True)
    print("rebalance smoke: add-brick + managed daemon converged "
          "(moved>=1, task row rendered, all four gftpu_rebalance_* "
          "families in the daemon's snapshot, bytes exact)")

asyncio.run(main())
EOF
rebal_rc=$?
if [ $rebal_rc -ne 0 ]; then
    echo "ci: rebalance smoke failed — not mergeable"
    exit $rebal_rc
fi

echo "== ci: process-plane smoke (workers=2 managed gateway,"
echo "       byte-exact PUT/GET, worker respawn) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, json, os, shutil, signal, tempfile, time

async def main():
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient
    from glusterfs_tpu.gateway.minihttp import fetch as http

    base = tempfile.mkdtemp(prefix="ci-procplane")
    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as c:
            await c.call("volume-create", name="pv", vtype="distribute",
                         bricks=[{"path": os.path.join(base, "b0")}])
            await c.call("volume-start", name="pv")
            await c.call("volume-set", name="pv",
                         key="gateway.workers", value="2")
            await c.call("volume-gateway", name="pv", action="start")
            port = 0
            for _ in range(600):
                st = await c.call("volume-gateway", name="pv",
                                  action="status")
                if st["gateway"]["online"] and st["gateway"]["port"]:
                    port = st["gateway"]["port"]
                    break
                await asyncio.sleep(0.1)
            assert port, f"worker-pool gateway never up: {st}"
            statusfile = os.path.join(d.workdir, "gateway-pv.workers")
            with open(statusfile) as f:
                wst = json.load(f)
            assert len(wst["workers"]) == 2, wst
            body = b"process-plane" * 300
            s = 0
            for _ in range(100):
                try:
                    s, _, _ = await http("127.0.0.1", port, "PUT", "/b")
                    if s == 200:
                        break
                except (ConnectionError, OSError):
                    pass
                await asyncio.sleep(0.1)
            assert s == 200, "pool unreachable"
            s, _, _ = await http("127.0.0.1", port, "PUT", "/b/k",
                                 body=body)
            assert s == 200, s
            s, _, data = await http("127.0.0.1", port, "GET", "/b/k")
            assert s == 200 and data == body, (s, len(data))
            # respawn: SIGKILL a worker, the pool recovers and serves
            os.kill(wst["workers"][0]["pid"], signal.SIGKILL)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with open(statusfile) as f:
                    wst2 = json.load(f)
                if wst2["respawns"] >= 1 and \
                        all(w["alive"] for w in wst2["workers"]):
                    break
                await asyncio.sleep(0.3)
            assert wst2["respawns"] >= 1, wst2
            ok = 0
            for _ in range(8):
                try:
                    s, _, data = await http("127.0.0.1", port, "GET",
                                            "/b/k")
                    if s == 200 and data == body:
                        ok += 1
                except (ConnectionError, OSError):
                    pass
                await asyncio.sleep(0.1)
            assert ok >= 6, f"pool dropped after worker kill ({ok}/8)"
            await c.call("volume-gateway", name="pv", action="stop")
    finally:
        await d.stop()
        shutil.rmtree(base, ignore_errors=True)
    print("process-plane smoke: managed workers=2 pool served "
          "byte-exact PUT/GET (mode=%s), worker SIGKILL respawned "
          "and kept serving" % wst["mode"])

asyncio.run(main())
EOF
procplane_rc=$?
if [ $procplane_rc -ne 0 ]; then
    echo "ci: process-plane smoke failed — not mergeable"
    exit $procplane_rc
fi

echo "== ci: lease smoke (hot GETs off the lease-held object cache at"
echo "       zero wire fops, recall coherence, gftpu_cache_*/gftpu_leases"
echo "       families, v15 volume-set keys) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, os, shutil, tempfile

from glusterfs_tpu.api.glfs import Client, wait_connected
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import walk
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.gateway import ClientPool, ObjectGateway
from glusterfs_tpu.gateway.minihttp import fetch as http
from glusterfs_tpu.protocol.client import ClientLayer

BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume locks
    type features/locks
    subvolumes posix
end-volume
volume leases
    type features/leases
    subvolumes locks
end-volume
volume upcall
    type features/upcall
    subvolumes leases
end-volume
"""
CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume upcall
end-volume
"""

def sample(snap, fam, **labels):
    return sum(v for l, v in snap.get(fam, {}).get("samples", [])
               if all(l.get(k) == lv for k, lv in labels.items()))

def wire(graphs):
    return sum(l.rpc_roundtrips for g in graphs for l in walk(g.top)
               if isinstance(l, ClientLayer))

async def main():
    base = tempfile.mkdtemp(prefix="lease-smoke")
    server = await serve_brick(BRICK.format(dir=os.path.join(base, "b")))
    vf = CLIENT.format(port=server.port)

    async def factory():
        c = Client(Graph.construct(vf))
        await c.mount()
        await wait_connected(c.graph)
        return c

    gw = ObjectGateway(ClientPool(factory, 2),
                       volume="leasev", object_cache_size=4 << 20)
    await gw.start()
    H, P = gw.host, gw.port
    fuse = await factory()
    payload = bytes(range(256)) * 128  # 32 KiB
    try:
        assert (await http(H, P, "PUT", "/b"))[0] == 200
        st, hd, _ = await http(H, P, "PUT", "/b/hot", body=payload)
        assert st == 200, st
        etag = hd["etag"]
        st, _, data = await http(H, P, "GET", "/b/hot")  # fills cache
        assert st == 200 and data == payload
        snap0 = REGISTRY.snapshot()
        n0 = wire(c.graph for c in gw.pool.clients)
        for _ in range(20):
            st, _, data = await http(H, P, "GET", "/b/hot")
            assert st == 200 and data == payload
        for _ in range(5):
            st, _, _ = await http(H, P, "GET", "/b/hot",
                                  headers={"if-none-match": etag})
            assert st == 304, st
        assert wire(c.graph for c in gw.pool.clients) == n0, \
            "hot-GET loop touched the wire"
        snap1 = REGISTRY.snapshot()
        h0 = sample(snap0, "gftpu_cache_hits_total", cache="gateway")
        h1 = sample(snap1, "gftpu_cache_hits_total", cache="gateway")
        assert h1 >= h0 + 25, f"gateway cache hits not monotonic " \
            f"({h0} -> {h1})"
        assert sample(snap1, "gftpu_cache_bytes_total",
                      cache="gateway") > 0
        assert sample(snap1, "gftpu_leases", state="held") >= 1, \
            "brick lease gauge empty while the cache serves"
        # recall coherence: an out-of-band overwrite drops the entry
        # before the ack; the next GET serves the new bytes
        v2 = b"recalled" * 4096
        await fuse.write_file("/b/hot", v2)
        for _ in range(100):
            if gw._ocache.dump()["objects"] == 0:
                break
            await asyncio.sleep(0.05)
        st, _, data = await http(H, P, "GET", "/b/hot")
        assert st == 200 and data == v2, "stale bytes after recall"
        snap2 = REGISTRY.snapshot()
        assert sample(snap2, "gftpu_lease_recalls_total",
                      reason="conflict") >= 1
    finally:
        await fuse.unmount()
        await gw.stop()
        await server.stop()

    # -- managed path: the op-version 15 volume-set keys ----------------
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)
    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as mc:
            await mc.call("volume-create", name="lv",
                          vtype="distribute",
                          bricks=[{"path": os.path.join(base, "vb0")}])
            await mc.call("volume-start", name="lv")
            await mc.call("volume-set", name="lv",
                          key="features.leases", value="on")
            for key, val in (("features.lease-timeout", "600"),
                             ("gateway.object-cache-size", "4MB")):
                r = await mc.call("volume-set", name="lv",
                                  key=key, value=val)
                assert r.get("ok", True), (key, r)
        m = await mount_volume(d.host, d.port, "lv")
        try:
            await m.write_file("/leased", b"managed" * 1024)
            assert await m.lease_acquire("/leased") is True, \
                "managed brick refused a lease grant"
            assert bytes(await m.read_file("/leased")) == \
                b"managed" * 1024
        finally:
            await m.unmount()
    finally:
        await d.stop()
        shutil.rmtree(base, ignore_errors=True)
    print("lease smoke: 25 hot GETs at zero wire fops, recall-exact "
          "coherence, cache/lease families monotonic, v15 keys accepted")

asyncio.run(main())
EOF
lease_rc=$?
if [ $lease_rc -ne 0 ]; then
    echo "ci: lease smoke failed — not mergeable"
    exit $lease_rc
fi

echo "== ci: qos smoke (per-client admission shed at a tight fops cap,"
echo "       gftpu_qos_* family monotonicity, live v16 volume-set flip,"
echo "       shaping column in volume-status-deep) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, os, shutil, tempfile

from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.daemon import serve_brick

BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume srv
    type protocol/server
    option qos on
    option qos-fops-per-sec 30
    option qos-burst 1
    subvolumes posix
end-volume
"""
CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume srv
end-volume
"""

def sample(snap, fam, **labels):
    return sum(v for l, v in snap.get(fam, {}).get("samples", [])
               if all(l.get(k) == lv for k, lv in labels.items()))

async def main():
    from glusterfs_tpu.core.layer import Loc
    base = tempfile.mkdtemp(prefix="qos-smoke")
    # -- in-process brick: the registry families are reachable --------
    server = await serve_brick(BRICK.format(dir=os.path.join(base, "b")))
    try:
        g = Graph.construct(CLIENT.format(port=server.port))
        await g.activate()
        for _ in range(200):
            if g.top.connected:
                break
            await asyncio.sleep(0.01)
        snap0 = REGISTRY.snapshot()
        for _ in range(60):  # ~30 past the burst at 30 fops/s
            await g.top.lookup(Loc("/"))
        assert g.top.qos_backoff_total > 0, \
            "client absorbed no sheds at a 30 fops/s cap"
        eng = server._qos["srv"]
        assert eng.stats["shed"] > 0, "brick engine counted no sheds"
        snap1 = REGISTRY.snapshot()
        t0 = sample(snap0, "gftpu_qos_throttled_fops_total")
        t1 = sample(snap1, "gftpu_qos_throttled_fops_total")
        assert t1 > t0, f"qos throttle family not monotonic ({t0}->{t1})"
        assert "gftpu_qos_tokens" in snap1, "token gauge family missing"
        rows = server._status_of(server.top, "clients")["clients"]
        assert any(r.get("qos", {}).get("shed_fops", 0) > 0
                   for r in rows), "no shaping column in client status"
        await g.fini()
    finally:
        await server.stop()

    # -- managed path: v16 volume-set keys + a LIVE flip ---------------
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)
    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as mc:
            await mc.call("volume-create", name="qv",
                          vtype="distribute",
                          bricks=[{"path": os.path.join(base, "vb0")}])
            await mc.call("volume-start", name="qv")
        m = await mount_volume(d.host, d.port, "qv")
        try:
            await m.write_file("/warm", b"q" * 4096)  # pre-flip baseline
            async with MgmtClient(d.host, d.port) as mc:
                for key, val in (("server.qos-fops-per-sec", "20"),
                                 ("server.qos-burst", "1"),
                                 ("server.qos", "on")):
                    r = await mc.call("volume-set", name="qv",
                                      key=key, value=val)
                    assert r.get("ok", True), (key, r)
            await asyncio.sleep(1.5)  # volfile watcher propagation
            for i in range(40):  # writes: reads are cache-served
                try:
                    await m.write_file(f"/f{i}", b"q" * 512)
                except FopError:  # graph-reload blip, one retry
                    await m.write_file(f"/f{i}", b"q" * 512)
            async with MgmtClient(d.host, d.port) as mc:
                deep = await mc.call("volume-status-deep", name="qv",
                                     what="clients")
            shed = sum(r.get("qos", {}).get("shed_fops", 0)
                       for b in deep["bricks"].values()
                       for r in b.get("clients", []))
            assert shed > 0, "live flip shed nothing at 20 fops/s"
        finally:
            await m.unmount()
    finally:
        await d.stop()
        shutil.rmtree(base, ignore_errors=True)
    print("qos smoke: admission sheds on both paths, qos families "
          "monotonic, v16 keys flip the plane live, shaping column "
          "populated")

asyncio.run(main())
EOF
qos_rc=$?
if [ $qos_rc -ne 0 ]; then
    echo "ci: qos smoke failed — not mergeable"
    exit $qos_rc
fi

echo "== ci: shm smoke (managed volume, bulk lane armed, families"
echo "       monotonic, live volume-set off downgrades inline) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, os, shutil, tempfile

async def main():
    from glusterfs_tpu.core.layer import walk
    from glusterfs_tpu.core.metrics import REGISTRY
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)
    from glusterfs_tpu.rpc import shm

    if not shm.supported():
        print("shm smoke: platform has no memfd/SCM_RIGHTS — skipped")
        return

    def fam(name):
        return sum(s[1] for s in REGISTRY.snapshot()[name]["samples"])

    base = tempfile.mkdtemp(prefix="ci-shm")
    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as c:
            await c.call("volume-create", name="sv", vtype="distribute",
                         bricks=[{"path": os.path.join(base, "b0")}])
            await c.call("volume-start", name="sv")
        cl = await mount_volume(d.host, d.port, "sv")
        try:
            def lanes():
                return [l for l in walk(cl.graph.top)
                        if l.type_name == "protocol/client"]

            for _ in range(200):  # subprocess brick: give arming time
                if lanes() and all(l._peer_shm for l in lanes()):
                    break
                await asyncio.sleep(0.05)
            assert lanes() and all(l._peer_shm for l in lanes()), \
                "bulk lane never armed against the managed brick"
            data = os.urandom(1 << 20)
            tx0, rx0 = fam("gftpu_shm_tx_bytes_total"), \
                fam("gftpu_shm_rx_bytes_total")
            await cl.write_file("/f", data)  # dd stand-in: 1 MiB
            got = bytes(await cl.read_file("/f"))
            assert got == data, "armed-lane bytes diverged"
            tx1, rx1 = fam("gftpu_shm_tx_bytes_total"), \
                fam("gftpu_shm_rx_bytes_total")
            assert tx1 - tx0 >= len(data), (tx0, tx1)
            assert rx1 - rx0 >= len(data), (rx0, rx1)
            # the per-connection state is on the status surface
            assert any(l.dump_private()["shm"]["armed"]
                       for l in lanes())

            # live downgrade: volume set off must drop BOTH directions
            # to inline with no reconnect and no byte damage
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-set", name="sv",
                             key="network.shm-transport", value="off")
            for _ in range(200):
                ls = lanes()
                if ls and all(not l.opts["shm-transport"] for l in ls):
                    break
                await asyncio.sleep(0.05)
            assert all(not l.opts["shm-transport"] for l in lanes()), \
                "volume-set never reached the mounted client"
            tx2 = fam("gftpu_shm_tx_bytes_total")
            data2 = os.urandom(1 << 20)
            await cl.write_file("/g", data2)
            assert bytes(await cl.read_file("/g")) == data2, \
                "inline downgrade bytes diverged"
            assert fam("gftpu_shm_tx_bytes_total") == tx2, \
                "a frame rode the lane after volume-set off"
        finally:
            await cl.unmount()
    finally:
        await d.stop()
        shutil.rmtree(base, ignore_errors=True)
    print("shm smoke: managed volume armed the bulk lane (families "
          "+1 MiB both directions), live volume-set off downgraded "
          "to inline, bytes exact throughout")

asyncio.run(main())
EOF
shm_rc=$?
if [ $shm_rc -ne 0 ]; then
    echo "ci: shm smoke failed — not mergeable"
    exit $shm_rc
fi

echo "== ci: incident smoke (managed volume, brick SIGKILL"
echo "       auto-captures, list shows it, show round-trips) =="
timeout -k 10 240 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, os, shutil, tempfile

async def main():
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    base = tempfile.mkdtemp(prefix="ci-inc")
    inc = os.path.join(base, "incidents")
    d = Glusterd(os.path.join(base, "gd"))
    await d.start()
    try:
        async with MgmtClient(d.host, d.port) as c:
            await c.call("volume-create", name="iv",
                         vtype="distribute",
                         bricks=[{"path": os.path.join(base, "b0")}])
            await c.call("volume-set", name="iv",
                         key="diagnostics.incident-dir", value=inc)
            await c.call("volume-set", name="iv",
                         key="diagnostics.incident-min-interval",
                         value="0")
            await c.call("volume-start", name="iv")
        cl = await mount_volume(d.host, d.port, "iv")
        try:
            await cl.write_file("/f", b"i" * 65536)
            assert bytes(await cl.read_file("/f")) == b"i" * 65536

            # brick SIGKILL is a failure-class event: the client's
            # BRICK_DISCONNECTED must auto-capture a local bundle into
            # the armed dir with no operator in the loop
            d.bricks["iv-brick-0"].kill()
            rows = []
            for _ in range(200):
                async with MgmtClient(d.host, d.port) as c:
                    rows = (await c.call("volume-incident-list",
                                         name="iv"))["bundles"]
                if rows:
                    break
                await asyncio.sleep(0.1)
            assert rows, "brick SIGKILL auto-captured no bundle"
            assert any("BRICK_DISCONNECTED" in r["name"]
                       for r in rows), rows

            # show must round-trip the bundle JSON (newest by default
            # AND by explicit name)
            async with MgmtClient(d.host, d.port) as c:
                shown = await c.call("volume-incident-show",
                                     name="iv")
                named = await c.call("volume-incident-show",
                                     name="iv",
                                     bundle=rows[-1]["name"])
            for b in (shown, named):
                assert b.get("reason"), b.keys()
                assert "spans" in b and "metrics" in b, b.keys()
        finally:
            await cl.unmount()
    finally:
        await d.stop()
        shutil.rmtree(base, ignore_errors=True)
    print("incident smoke: brick kill auto-captured a "
          "BRICK_DISCONNECTED bundle, list surfaced it, show "
          "round-tripped the JSON")

asyncio.run(main())
EOF
inc_rc=$?
if [ $inc_rc -ne 0 ]; then
    echo "ci: incident smoke failed — not mergeable"
    exit $inc_rc
fi

echo "== ci: alert smoke (v19 slo-rules, error-gen storm raises, UDP"
echo "       event + auto-captured bundle, clears on healthy traffic) =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python - <<'EOF'
import asyncio, json, os, shutil, tempfile

async def main():
    from glusterfs_tpu.core import events as gf_events
    from glusterfs_tpu.core.fops import FopError
    from glusterfs_tpu.mgmt.eventsd import EventsDaemon
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    base = tempfile.mkdtemp(prefix="ci-alert")
    inc = os.path.join(base, "incidents")
    rules = json.dumps([{
        "name": "readv-errors", "kind": "error-ratio",
        "errors": "gftpu_fop_errors_total",
        "total": "gftpu_fops_total",
        "labels": {"op": "readv"},
        "target": 0.05, "window": 4,
    }], separators=(",", ":"))
    ev = EventsDaemon()
    udp, _ctl = await ev.start()
    os.environ["GFTPU_EVENTSD"] = f"127.0.0.1:{udp}"
    gf_events.configure(f"127.0.0.1:{udp}")
    d = Glusterd(os.path.join(base, "gd"))
    try:
        await d.start()
        async with MgmtClient(d.host, d.port) as c:
            await c.call("volume-create", name="av",
                         vtype="distribute",
                         bricks=[{"path": os.path.join(base, "b0")}])
            await c.call("volume-start", name="av")
            for k, v in (("diagnostics.history-interval", "0.25"),
                         ("diagnostics.slo-rules", rules),
                         ("diagnostics.incident-dir", inc),
                         ("diagnostics.incident-min-interval", "0")):
                await c.call("volume-set", name="av", key=k, value=v)
        m = await mount_volume(d.host, d.port, "av")
        try:
            await m.write_file("/f", b"x" * 8192)
            assert bytes(await m.read_file("/f")) == b"x" * 8192
            # ARM THE STORM: every readv on the brick fails
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-set", name="av",
                             key="debug.error-gen", value="on")
                await c.call("volume-set", name="av",
                             key="debug.error-fops", value="readv")
                await c.call("volume-set", name="av",
                             key="debug.error-failure", value="100")
            deadline = asyncio.get_event_loop().time() + 60
            active = []
            while asyncio.get_event_loop().time() < deadline:
                try:
                    await m.read_file("/f")
                except FopError:
                    pass
                out = await d.op_volume_alerts("av")
                active = [a for a in out["active"]
                          if a["rule"] == "readv-errors"]
                if active:
                    break
                await asyncio.sleep(0.3)
            assert active, "storm never raised the alert"
            assert active[0]["observed"] > 0.05, active[0]
            raised = [e for e in ev.recent
                      if e.get("event") == "ALERT_RAISED"]
            assert raised, "ALERT_RAISED never reached eventsd"
            caps = []
            deadline = asyncio.get_event_loop().time() + 20
            while asyncio.get_event_loop().time() < deadline:
                caps = [f for f in (os.listdir(inc)
                                    if os.path.isdir(inc) else [])
                        if "ALERT_RAISED" in f]
                if caps:
                    break
                await asyncio.sleep(0.3)
            assert caps, "alert auto-captured no incident bundle"
            with open(os.path.join(inc, caps[0])) as f:
                bundle = json.load(f)
            ramp = [pts for k, pts in bundle["history"]["series"].items()
                    if k.startswith("gftpu_fop_errors_total")]
            assert ramp and any(p[-1][1] > p[0][1] for p in ramp), \
                "bundle history shows no error ramp"
            # clear by shifting traffic to writes (only readv storms);
            # no volume-set, so the raising process keeps its history
            deadline = asyncio.get_event_loop().time() + 60
            while asyncio.get_event_loop().time() < deadline:
                await m.write_file("/f", b"y" * 4096)
                out = await d.op_volume_alerts("av")
                if not out["active"]:
                    break
                await asyncio.sleep(0.3)
            assert out["active"] == [], "alert never cleared"
            hist = await d.op_volume_alerts("av", "history")
            edges = [t["edge"] for t in hist["history"]
                     if t["rule"] == "readv-errors"]
            assert "RAISED" in edges and "CLEARED" in edges, edges
        finally:
            await m.unmount()
    finally:
        await d.stop()
        os.environ.pop("GFTPU_EVENTSD", None)
        gf_events.configure(None)
        await ev.stop()
        shutil.rmtree(base, ignore_errors=True)
    print("alert smoke: error-gen storm raised the error-ratio alert "
          "(UDP event + auto-captured bundle with the error ramp), "
          "healthy traffic cleared it, both edges in alert history")

asyncio.run(main())
EOF
alert_rc=$?
if [ $alert_rc -ne 0 ]; then
    echo "ci: alert smoke failed — not mergeable"
    exit $alert_rc
fi

if [ $gate_rc -eq 2 ]; then
    echo "ci: green, but flaky tests were seen (flake gate exit 2)"
    exit 2
fi
echo "ci: mergeable (two identical green tier-1 runs + bench contract"
echo "    + metrics smoke + gateway smoke + concurrency smoke"
echo "    + mesh smoke + chaos smoke + delta-write smoke"
echo "    + rebalance smoke + process-plane smoke + lease smoke"
echo "    + qos smoke + shm smoke + incident smoke + alert smoke)"
exit 0
