#!/usr/bin/env bash
# ci.sh — the one-command pre-merge gate (ISSUE 3 satellite; the
# regression signal ROADMAP's tier-1 bar depends on):
#
#   1. tools/flake_gate.sh      tier-1 twice, diffing the failure sets
#                               (stable failures -> exit 1, flakes -> 2)
#   2. bench contract test      the driver-facing reporting contract
#                               (compact parseable headline + detail
#                               file) — a broken emit() loses a whole
#                               round's record, so it gates merges even
#                               though the full bench doesn't
#
# Usage:  tools/ci.sh [extra pytest args for the tier-1 runs...]
# Exit: first failing stage's code; 0 = mergeable.

set -u
cd "$(dirname "$0")/.."

echo "== ci: flake gate (tier-1 x2) =="
tools/flake_gate.sh "$@"
gate_rc=$?
if [ $gate_rc -eq 1 ]; then
    echo "ci: STABLE tier-1 failures — not mergeable"
    exit 1
fi

echo "== ci: bench reporting contract =="
timeout -k 10 300 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_bench_contract.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
bench_rc=$?
if [ $bench_rc -ne 0 ]; then
    echo "ci: bench contract broken — not mergeable"
    exit $bench_rc
fi

if [ $gate_rc -eq 2 ]; then
    echo "ci: green, but flaky tests were seen (flake gate exit 2)"
    exit 2
fi
echo "ci: mergeable (two identical green tier-1 runs + bench contract)"
exit 0
