"""GL03 — async discipline: blocking calls inside ``async def``.

Historical bug: PR 12's review moved two blocking waits off-loop
(daemon teardown joins stalling the event loop); the 2-core chaos host
turns any such stall directly into serving-p99.

Flagged inside ``async def`` bodies (nested sync ``def``/``lambda``
bodies are their own scope and exempt):

* ``time.sleep(...)``
* ``subprocess.run / call / check_call / check_output`` (``Popen``
  construction is spawn-and-return and allowed)
* non-awaited ``.wait(...)`` / ``.communicate(...)`` — the blocking
  subprocess shapes; awaited forms (``await proc.wait()``) and calls
  passed into asyncio wrappers (``wait_for``/``shield``/
  ``ensure_future``/``create_task``/``gather``/``to_thread``) are the
  async forms and pass
* zero-argument ``.join()`` (thread/process join; ``sep.join(it)`` and
  ``os.path.join(a, b)`` always carry arguments)
* zero-argument ``.result()`` (a concurrent.futures block; asyncio
  futures are awaited, not ``.result()``-polled)

The remedy is ``await asyncio.to_thread(...)`` (or the asyncio-native
primitive); a deliberate block carries a pragma with its reason.
"""

from __future__ import annotations

import ast

from .astutil import dotted
from .engine import Finding, RepoIndex

_BLOCKING_SUBPROCESS = {"subprocess.run", "subprocess.call",
                        "subprocess.check_call",
                        "subprocess.check_output"}
_ASYNC_WRAPPERS = {"wait_for", "shield", "ensure_future", "create_task",
                   "gather", "to_thread", "run_coroutine_threadsafe",
                   "wait", "as_completed", "timeout", "timeout_at"}


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list[Finding] = []
        self._async_depth = 0
        self._exempt: set[int] = set()  # node ids inside wrappers/awaits

    # -- scope tracking ----------------------------------------------------

    def visit_AsyncFunctionDef(self, node):
        self._async_depth += 1
        self.generic_visit(node)
        self._async_depth -= 1

    def visit_FunctionDef(self, node):
        saved, self._async_depth = self._async_depth, 0
        self.generic_visit(node)
        self._async_depth = saved

    def visit_Lambda(self, node):
        saved, self._async_depth = self._async_depth, 0
        self.generic_visit(node)
        self._async_depth = saved

    # -- exemption marking -------------------------------------------------

    def visit_Await(self, node):
        if isinstance(node.value, ast.Call):
            self._exempt.add(id(node.value))
        self.generic_visit(node)

    def _mark_wrapper_args(self, call: ast.Call) -> None:
        name = dotted(call.func)
        if name.split(".")[-1] in _ASYNC_WRAPPERS:
            for a in list(call.args) + [k.value for k in call.keywords]:
                for n in ast.walk(a):
                    if isinstance(n, ast.Call):
                        self._exempt.add(id(n))

    # -- the check ---------------------------------------------------------

    def visit_Call(self, node):
        self._mark_wrapper_args(node)
        if self._async_depth and id(node) not in self._exempt:
            self._flag(node)
        self.generic_visit(node)

    def _flag(self, node: ast.Call) -> None:
        name = dotted(node.func)
        msg = None
        if name == "time.sleep":
            msg = "time.sleep blocks the event loop — use " \
                  "await asyncio.sleep"
        elif name in _BLOCKING_SUBPROCESS:
            msg = f"{name} blocks until the child exits — use " \
                  "asyncio.create_subprocess_exec or " \
                  "await asyncio.to_thread(...)"
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            nargs = len(node.args) + len(node.keywords)
            if attr in ("wait", "communicate"):
                msg = f".{attr}() here is the blocking form — await " \
                      "it, wrap it in an asyncio primitive, or move " \
                      "it off-loop with await asyncio.to_thread(...)"
            elif attr in ("join", "result") and nargs == 0:
                msg = f".{attr}() with no arguments is a blocking " \
                      "thread/future primitive — move it off-loop " \
                      "(await asyncio.to_thread) or await the " \
                      "asyncio-native form"
        if msg is not None:
            self.findings.append(Finding(
                "GL03", self.path, node.lineno,
                f"blocking call inside async def: {msg}"))


def check(idx: RepoIndex) -> list[Finding]:
    out: list[Finding] = []
    for sf in idx.code.values():
        if sf.tree is None:
            continue
        v = _Visitor(sf.path)
        v.visit(sf.tree)
        out.extend(v.findings)
    return out
