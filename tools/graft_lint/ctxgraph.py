"""Execution-context reachability: which code runs on an event loop,
which on a daemon thread (graft-race's shared analysis, GL06-GL09).

The repo is a hybrid runtime — asyncio loops per brick/gateway/daemon
interwoven with daemon threads (event-pool workers, codec flush pools,
mesh warm/probe threads, the fuse reader/writer split, worker-pool
supervisors).  The reference keeps the analogous planes apart by
contract (gf-event threads vs syncop continuation context); here the
contract is machine-checked, which needs to know, per function, the
execution context(s) it can run under.

Seeding:

* **loop** — every ``async def`` body (coroutines only ever run on a
  loop), plus sync callables handed to the loop by name:
  ``call_soon_threadsafe`` / ``call_soon`` / ``call_later`` /
  ``call_at`` / ``add_reader`` / ``add_writer`` / ``add_done_callback``
  / ``add_signal_handler`` arguments.
* **thread** — ``threading.Thread(target=...)`` targets, every
  function-valued argument of a ``.submit(...)`` (executor pools and
  the event pool's keyed submit), ``asyncio.to_thread(fn, ...)`` and
  ``loop.run_in_executor(pool, fn, ...)`` payloads, and the
  declarative entries in :data:`tables.CTX_THREAD_ENTRY` (dynamic
  dispatch the syntax cannot see).

Contexts then propagate through the *direct* call graph: a sync
function called from loop-context code is loop-reachable, one called
from a thread entry is thread-reachable, and a function can be both.
Crucially, handing a callable ACROSS the boundary is not a call edge —
``loop.call_soon_threadsafe(done)`` from a worker thread seeds ``done``
as loop context, exactly the re-entry the runtime performs.

Resolution is deliberately shallow but honest: ``self.method`` within
a class, module-level names within a file, ``from ..x import y`` /
``import a.b as c`` across files.  Unresolvable dynamic dispatch means
a function stays context-UNKNOWN and the checkers skip it — the
declarative entry tables exist to close exactly those gaps, as data.
"""

from __future__ import annotations

import ast
import dataclasses

from .astutil import call_name, dotted
from .engine import RepoIndex

LOOP = "loop"
THREAD = "thread"

#: last-component call names whose function-ref arguments run on a
#: thread (position: which args to consider; None = all)
_THREAD_HANDOFF = {"submit": None, "to_thread": (0,),
                   "run_in_executor": (1,)}
#: last-component call names whose function-ref arguments run on the
#: loop (the thread->loop re-entry points).  ``add_done_callback`` is
#: handled separately: asyncio tasks/futures run callbacks on their
#: loop, but concurrent.futures runs them in the COMPLETING THREAD —
#: it only seeds loop when the receiver provably came from
#: create_task/ensure_future/create_future in the same function.
_LOOP_HANDOFF = {"call_soon_threadsafe": None, "call_soon": None,
                 "call_later": None, "call_at": None, "add_reader": None,
                 "add_writer": None, "add_signal_handler": None}


@dataclasses.dataclass
class FuncInfo:
    qual: str                 # "<relpath>::<Scope.dotted.name>"
    path: str
    scope: str                # dotted name within the file
    node: ast.AST             # FunctionDef / AsyncFunctionDef / Lambda
    cls: str | None           # innermost enclosing class, if any
    is_async: bool
    calls: list[str] = dataclasses.field(default_factory=list)
    #: own parameter names (for forwarder detection)
    params: list[str] = dataclasses.field(default_factory=list)
    #: (call node, resolved target qual) pairs, for the forwarder
    #: fixpoint
    callsites: list = dataclasses.field(default_factory=list)
    #: (owner_qual, param) for own-or-ancestor params this function
    #: CALLS directly (makes the owner a context forwarder once this
    #: function has a context)
    param_calls: list = dataclasses.field(default_factory=list)
    #: (owner_qual, param, side) for params handed straight to a
    #: thread/loop handoff (unconditional forwarders)
    param_handoffs: list = dataclasses.field(default_factory=list)
    #: resolver closure bound to this function's scope (set in pass 2)
    resolver: object = None

    @property
    def lineno(self) -> int:
        return self.node.lineno

    def body_walk(self):
        """Walk this function's own body, NOT descending into nested
        function/lambda bodies (they are their own FuncInfos) but
        including comprehension bodies (those execute inline)."""
        stack = list(ast.iter_child_nodes(self.node))
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))


class _FileScope:
    """Per-file name environment for shallow resolution."""

    def __init__(self, path: str):
        self.path = path
        self.module_funcs: dict[str, str] = {}    # name -> qual
        self.classes: dict[str, dict[str, str]] = {}  # cls -> meth -> qual
        self.mod_alias: dict[str, str] = {}       # alias -> module dotted
        self.from_imports: dict[str, tuple[str, str]] = {}  # name ->
        #                                           (module dotted, name)


class ContextGraph:
    def __init__(self) -> None:
        self.funcs: dict[str, FuncInfo] = {}
        self.loop: set[str] = set()
        self.thread: set[str] = set()
        #: qual -> (caller qual or seed description) for rendering the
        #: reachability chain in findings
        self.why_loop: dict[str, str] = {}
        self.why_thread: dict[str, str] = {}
        self._mod_to_path: dict[str, str] = {}
        self._children: dict[tuple[str, str], dict[str, str]] = {}
        self._by_path: dict[str, list["FuncInfo"]] = {}

    # -- queries -----------------------------------------------------------

    def ctx(self, qual: str) -> frozenset:
        out = set()
        if qual in self.loop:
            out.add(LOOP)
        if qual in self.thread:
            out.add(THREAD)
        return frozenset(out)

    def chain(self, qual: str, ctx: str, limit: int = 4) -> str:
        """Render how ``qual`` got its context, for finding messages."""
        why = self.why_thread if ctx == THREAD else self.why_loop
        hops, cur, seen = [], qual, set()
        while cur in why and cur not in seen and len(hops) < limit:
            seen.add(cur)
            cur = why[cur]
            hops.append(cur.split("::")[-1] if "::" in cur else cur)
        return " <- ".join(hops)

    def methods_of(self, path: str, cls: str) -> list[FuncInfo]:
        return [fi for fi in self._by_path.get(path, ())
                if fi.cls == cls]


def _module_of(path: str) -> str:
    mod = path[:-3] if path.endswith(".py") else path
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def build(idx: RepoIndex) -> ContextGraph:
    """Build (and memoize on the index) the context graph for the
    scanned code files."""
    cached = getattr(idx, "_ctxgraph", None)
    if cached is not None:
        return cached
    g = ContextGraph()
    mod_to_path = {}
    for path in idx.code:
        mod_to_path[_module_of(path)] = path
    g._mod_to_path = mod_to_path

    scopes: dict[str, _FileScope] = {}
    seeds_thread: list[tuple[str, str]] = []   # (qual, why)
    seeds_loop: list[tuple[str, str]] = []

    # pass 1: index every function and the per-file name environment
    for path, sf in idx.code.items():
        if sf.tree is None:
            continue
        fs = _FileScope(path)
        scopes[path] = fs
        _index_file(g, fs, sf.tree, mod_to_path)

    # index nested defs by (path, parent scope) and functions by path
    # once — pass 2 runs per call site and must not rescan the graph
    g._children = {}
    by_path: dict[str, list[FuncInfo]] = {}
    for qual, fi2 in g.funcs.items():
        parent = fi2.scope.rsplit(".", 1)[0] \
            if "." in fi2.scope else ""
        g._children.setdefault((fi2.path, parent), {})[
            fi2.scope.split(".")[-1]] = qual
        by_path.setdefault(fi2.path, []).append(fi2)
    g._by_path = by_path

    # pass 2: call edges + handoff seeds
    for path, sf in idx.code.items():
        if sf.tree is None:
            continue
        fs = scopes[path]
        for fi in by_path.get(path, ()):
            _extract_calls(g, fs, fi, seeds_thread, seeds_loop)
        # module-level statements spawn threads too (rare but legal)
        mod_fi = FuncInfo(qual=f"{path}::<module>", path=path,
                          scope="<module>", node=sf.tree, cls=None,
                          is_async=False)
        _extract_calls(g, fs, mod_fi, seeds_thread, seeds_loop)

    # pass 3: declarative entries (tables.py — dynamic dispatch the
    # syntax cannot see) with stale-entry detection left to GL06
    from . import tables
    for qual, reason in tables.CTX_THREAD_ENTRY.items():
        if qual in g.funcs:
            seeds_thread.append((qual, f"tables.CTX_THREAD_ENTRY "
                                       f"({reason})"))
    for qual, reason in tables.CTX_LOOP_ENTRY.items():
        if qual in g.funcs:
            seeds_loop.append((qual, f"tables.CTX_LOOP_ENTRY "
                                     f"({reason})"))

    # pass 4: propagate to a fixpoint with forwarder discovery.  async
    # bodies are loop seeds by construction; contexts flow only into
    # SYNC callees (an async callee's body is already loop, and a
    # thread cannot run a coroutine body by calling the function — it
    # only gets a coroutine object).  Forwarders close the one-hop
    # higher-order gap: a function handing its own parameter to
    # ``.submit``/``to_thread``/``run_in_executor`` (or calling it
    # while itself context-classified) turns its call sites' function
    # arguments into seeds of that context.
    for qual, fi in g.funcs.items():
        if fi.is_async:
            seeds_loop.append((qual, "async def (coroutines only ever "
                                     "run on a loop)"))
    forwarders: dict[str, set[tuple[str, str]]] = {
        THREAD: set(), LOOP: set()}
    for fi in g.funcs.values():
        for owner, param, side in fi.param_handoffs:
            forwarders[side].add((owner, param))
    for _ in range(12):  # bounded fixpoint (depth of forward chains)
        g.loop, g.thread = set(), set()
        g.why_loop, g.why_thread = {}, {}
        _propagate(g, seeds_loop, g.loop, g.why_loop)
        _propagate(g, seeds_thread, g.thread, g.why_thread,
                   sync_only_seeds=True)
        grew = False
        # a context-classified function that calls its (or a lexical
        # ancestor's) parameter executes the callable in that context
        for qual, fi in g.funcs.items():
            for side, members in ((THREAD, g.thread), (LOOP, g.loop)):
                if qual not in members:
                    continue
                for owner, param in fi.param_calls:
                    if (owner, param) not in forwarders[side]:
                        forwarders[side].add((owner, param))
                        grew = True
        # resolve call-site arguments feeding forwarder params
        before = (len(seeds_thread), len(seeds_loop))
        by_target: dict[str, dict[str, list[str]]] = {}
        for side in (THREAD, LOOP):
            for owner, param in forwarders[side]:
                by_target.setdefault(owner, {}).setdefault(
                    side, []).append(param)
        for fi in g.funcs.values():
            for call, target in fi.callsites:
                if target is None or target not in by_target:
                    continue
                tfi = g.funcs.get(target)
                if tfi is None:
                    continue
                for side, seeds in ((THREAD, seeds_thread),
                                    (LOOP, seeds_loop)):
                    for param in by_target[target].get(side, ()):
                        owner = target
                        expr = _arg_for(call, tfi, param)
                        if expr is None:
                            continue
                        t = fi.resolver(expr) if fi.resolver else None
                        if t is not None:
                            entry = (t, f"forwarded into {side} "
                                        f"context by {target} at "
                                        f"{fi.path}:{call.lineno}")
                            if entry not in seeds:
                                seeds.append(entry)
                                grew = True
                        elif isinstance(expr, ast.Name):
                            o2 = _param_owner(g, fi, expr.id)
                            if o2 is not None and \
                                    (o2, expr.id) not in \
                                    forwarders[side]:
                                forwarders[side].add((o2, expr.id))
                                grew = True
        if not grew and (len(seeds_thread),
                         len(seeds_loop)) == before:
            break
    idx._ctxgraph = g
    return g


def _param_owner(g: ContextGraph, fi: FuncInfo,
                 name: str) -> str | None:
    """qual of the function (fi or a lexical ancestor) owning param
    ``name``."""
    scope = fi.scope
    while True:
        qual = f"{fi.path}::{scope}"
        owner = g.funcs.get(qual)
        if owner is not None and name in owner.params:
            return qual
        if "." not in scope:
            return None
        scope = scope.rsplit(".", 1)[0]


def _arg_for(call: ast.Call, target: FuncInfo,
             param: str) -> ast.AST | None:
    """The call-site expression feeding ``param`` of ``target``."""
    for kw in call.keywords:
        if kw.arg == param:
            return kw.value
    if param not in target.params:
        return None
    idx = target.params.index(param)
    # a method called as self.m(...) / obj.m(...) binds params[0]
    # implicitly
    if target.cls is not None and target.params and \
            target.params[0] in ("self", "cls") and \
            isinstance(call.func, ast.Attribute):
        idx -= 1
    if 0 <= idx < len(call.args):
        a = call.args[idx]
        if isinstance(a, ast.Starred):
            return None
        return a
    return None


def _propagate(g: ContextGraph, seeds, out: set, why: dict,
               sync_only_seeds: bool = False) -> None:
    work = []
    for qual, reason in seeds:
        fi = g.funcs.get(qual)
        if fi is None:
            continue
        if sync_only_seeds and fi.is_async:
            continue  # a thread "running" a coroutine fn is just a bug
        if qual not in out:
            out.add(qual)
            why[qual] = reason
            work.append(qual)
    while work:
        cur = work.pop()
        for callee in g.funcs[cur].calls:
            fi = g.funcs.get(callee)
            if fi is None or fi.is_async or callee in out:
                continue
            out.add(callee)
            why[callee] = cur
            work.append(callee)


# -- pass 1: indexing ------------------------------------------------------


def _index_file(g: ContextGraph, fs: _FileScope, tree: ast.Module,
                mod_to_path: dict[str, str]) -> None:
    pkg_parts = fs.path.split("/")[:-1]

    def resolve_module(level: int, module: str | None) -> str | None:
        if level == 0:
            return module
        base = pkg_parts[: len(pkg_parts) - (level - 1)]
        mod = ".".join(base + ([module] if module else []))
        return mod or None

    for stmt in ast.walk(tree):
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                fs.mod_alias[alias.asname or alias.name.split(".")[0]] \
                    = alias.name
        elif isinstance(stmt, ast.ImportFrom):
            mod = resolve_module(stmt.level, stmt.module)
            if mod is None:
                continue
            for alias in stmt.names:
                name = alias.asname or alias.name
                if f"{mod}.{alias.name}" in mod_to_path:
                    # ``from ..core import metrics`` — a module import
                    fs.mod_alias[name] = f"{mod}.{alias.name}"
                else:
                    fs.from_imports[name] = (mod, alias.name)

    def visit(node: ast.AST, scope: list[str], cls: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                fs.classes.setdefault(child.name, {})
                visit(child, scope + [child.name], child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                dotted_scope = ".".join(scope + [child.name])
                qual = f"{fs.path}::{dotted_scope}"
                fi = FuncInfo(
                    qual=qual, path=fs.path, scope=dotted_scope,
                    node=child, cls=cls,
                    is_async=isinstance(child, ast.AsyncFunctionDef))
                g.funcs[qual] = fi
                if not scope:
                    fs.module_funcs[child.name] = qual
                elif cls is not None and scope[-1] == cls:
                    fs.classes[cls][child.name] = qual
                visit(child, scope + [child.name], cls)
            elif isinstance(child, ast.Lambda):
                dotted_scope = ".".join(
                    scope + [f"<lambda@{child.lineno}>"])
                qual = f"{fs.path}::{dotted_scope}"
                g.funcs[qual] = FuncInfo(
                    qual=qual, path=fs.path, scope=dotted_scope,
                    node=child, cls=cls, is_async=False)
                visit(child, scope + [f"<lambda@{child.lineno}>"], cls)
            else:
                visit(child, scope, cls)

    visit(tree, [], None)


# -- pass 2: call edges + handoff seeds ------------------------------------


def _extract_calls(g: ContextGraph, fs: _FileScope, fi: FuncInfo,
                   seeds_thread: list, seeds_loop: list) -> None:
    # nested defs visible by name from this function's body
    prefix = "" if fi.scope == "<module>" else fi.scope + "."
    local_defs = g._children.get(
        (fi.path, "" if fi.scope == "<module>" else fi.scope), {})

    def resolve(expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Lambda):
            return f"{fs.path}::{prefix}<lambda@{expr.lineno}>" \
                if f"{fs.path}::{prefix}<lambda@{expr.lineno}>" \
                in g.funcs else None
        if isinstance(expr, ast.Name):
            n = expr.id
            if n in local_defs:
                return local_defs[n]
            if n in fs.module_funcs:
                return fs.module_funcs[n]
            if n in fs.classes:  # constructing a class calls __init__
                return fs.classes[n].get("__init__")
            if n in fs.from_imports:
                mod, orig = fs.from_imports[n]
                from_path = _mod_path(mod)
                if from_path is not None:
                    q = f"{from_path}::{orig}"
                    return q if q in g.funcs else None
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and fi.cls is not None:
                    return fs.classes.get(fi.cls, {}).get(expr.attr)
                alias = fs.mod_alias.get(base.id)
                if alias is not None:
                    from_path = _mod_path(alias)
                    if from_path is not None:
                        q = f"{from_path}::{expr.attr}"
                        return q if q in g.funcs else None
        return None

    def _mod_path(mod: str) -> str | None:
        return g._mod_to_path.get(mod)

    def unwrap(expr: ast.AST) -> ast.AST:
        """functools.partial(fn, ...) hands off fn."""
        if isinstance(expr, ast.Call) and \
                dotted(expr.func).split(".")[-1] == "partial" and \
                expr.args:
            return expr.args[0]
        return expr

    args_node = getattr(fi.node, "args", None)
    if args_node is not None:
        fi.params = [a.arg for a in
                     args_node.posonlyargs + args_node.args +
                     args_node.kwonlyargs]
    fi.resolver = resolve

    # names provably bound to asyncio tasks/futures in this function —
    # their add_done_callback callbacks run on the loop (a cf.Future's
    # run in the completing worker thread, so anything else stays
    # context-UNKNOWN)
    asyncio_names: set[str] = set()
    for n in fi.body_walk():
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name) and \
                isinstance(n.value, ast.Call) and \
                call_name(n.value.func) in ("create_task",
                                            "ensure_future",
                                            "create_future"):
            asyncio_names.add(n.targets[0].id)

    def handoff(expr: ast.AST, side: str, why: str) -> None:
        expr = unwrap(expr)
        t = resolve(expr)
        if t is not None:
            (seeds_thread if side == "thread"
             else seeds_loop).append((t, why))
        elif isinstance(expr, ast.Name):
            owner = _param_owner(g, fi, expr.id)
            if owner is not None:
                fi.param_handoffs.append((owner, expr.id, side))

    for n in fi.body_walk():
        if not isinstance(n, ast.Call):
            continue
        name = dotted(n.func)
        last = name.split(".")[-1] if name else \
            (n.func.attr if isinstance(n.func, ast.Attribute) else "")
        # direct call edge
        target = resolve(n.func)
        if target is not None:
            fi.calls.append(target)
        fi.callsites.append((n, target))
        # calling a bare name that is a parameter (own or lexical
        # ancestor's): the owner is a context forwarder once this
        # function is classified
        if target is None and isinstance(n.func, ast.Name):
            owner = _param_owner(g, fi, n.func.id)
            if owner is not None:
                fi.param_calls.append((owner, n.func.id))
        # thread spawn: threading.Thread(target=...)
        if last == "Thread":
            for kw in n.keywords:
                if kw.arg == "target":
                    handoff(kw.value, "thread",
                            f"threading.Thread target at "
                            f"{fi.path}:{n.lineno}")
        elif last in _THREAD_HANDOFF:
            pos = _THREAD_HANDOFF[last]
            for i, a in enumerate(n.args):
                if pos is not None and i not in pos:
                    continue
                handoff(a, "thread",
                        f".{last}() handoff at {fi.path}:{n.lineno}")
        elif last in _LOOP_HANDOFF:
            for a in list(n.args) + [k.value for k in n.keywords]:
                handoff(a, "loop",
                        f".{last}() loop re-entry at "
                        f"{fi.path}:{n.lineno}")
        elif last == "add_done_callback" and \
                isinstance(n.func, ast.Attribute) and \
                isinstance(n.func.value, ast.Name) and \
                n.func.value.id in asyncio_names:
            for a in n.args:
                handoff(a, "loop",
                        f"asyncio done-callback at "
                        f"{fi.path}:{n.lineno}")
