"""GL06 — loop/thread boundary discipline (graft-race).

Historical bugs this encodes:

* PR 12: a GC'd passed-fd serve task reset a live connection — the
  thread/loop handoff around task creation is exactly where lifetime
  and affinity mistakes land.
* PR 7: an orphaned event-pool future wedged its connection — resolved
  from a worker thread without ``call_soon_threadsafe`` it would have
  raced the loop instead.

Two directions, both over :mod:`ctxgraph`'s reachability (the gap
GL03's purely syntactic in-``async def`` check cannot see):

* **thread-context** code must not touch loop-affine APIs —
  ``create_task`` / ``ensure_future``, ``Future.set_result`` /
  ``set_exception``, or ``<task>.cancel()`` — except through the
  ``call_soon_threadsafe`` / ``run_coroutine_threadsafe`` re-entry
  points (their callables are seeded as LOOP context by ctxgraph, so
  code inside them is exempt by construction).  asyncio's loop and its
  futures are not thread-safe; the runtime only promises these two
  doors.
* **loop-context** sync code must not block: ``.result()`` on a
  concurrent future, ``time.sleep``, the blocking ``subprocess``
  family, zero-argument ``.join()``, ``.wait(...)`` /
  ``.communicate(...)`` on subprocess/event objects.  (Inside ``async
  def`` GL03 already flags these; GL06 extends the same discipline to
  sync functions *reachable from* loop context.)

Stale declarative entries (:data:`tables.CTX_THREAD_ENTRY` /
``CTX_LOOP_ENTRY`` naming functions that no longer exist) are findings
too — the tables must not rot.
"""

from __future__ import annotations

import ast

from . import ctxgraph, tables
from .astutil import dotted
from .engine import Finding, RepoIndex

#: loop-affine call names (last component) illegal from thread context
_LOOP_AFFINE = {"create_task", "ensure_future"}
#: future-resolution calls illegal from thread context on an asyncio
#: future (concurrent.futures handoffs are declared in tables)
_FUTURE_RESOLVE = {"set_result", "set_exception"}

_BLOCKING_SUBPROCESS = {"subprocess.run", "subprocess.call",
                        "subprocess.check_call",
                        "subprocess.check_output"}
#: asyncio wrappers whose call arguments are not themselves executed
#: on the spot (mirrors GL03's exemption)
_ASYNC_WRAPPERS = {"wait_for", "shield", "ensure_future", "create_task",
                   "gather", "to_thread", "run_coroutine_threadsafe",
                   "wait", "as_completed", "timeout", "timeout_at"}


def _wrapper_exempt_ids(fi: ctxgraph.FuncInfo) -> set[int]:
    out: set[int] = set()
    for n in fi.body_walk():
        if isinstance(n, ast.Call):
            name = dotted(n.func)
            if name.split(".")[-1] in _ASYNC_WRAPPERS:
                for a in list(n.args) + [k.value for k in n.keywords]:
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Call):
                            out.add(id(sub))
    return out


def _thread_findings(g: ctxgraph.ContextGraph,
                     fi: ctxgraph.FuncInfo) -> list[Finding]:
    out = []
    chain = g.chain(fi.qual, ctxgraph.THREAD)
    via = f" (thread-reachable via {chain})" if chain else ""
    for n in fi.body_walk():
        if not isinstance(n, ast.Call) or \
                not isinstance(n.func, ast.Attribute):
            continue
        attr = n.func.attr
        recv = dotted(n.func.value)
        if attr in _LOOP_AFFINE:
            out.append(Finding(
                "GL06", fi.path, n.lineno,
                f"thread-context code calls .{attr}() — the loop is "
                f"not thread-safe; hand the callable over with "
                f"loop.call_soon_threadsafe or use "
                f"asyncio.run_coroutine_threadsafe{via}"))
        elif attr in _FUTURE_RESOLVE:
            key = f"{fi.path}::{fi.scope}"
            if key in tables.THREADSAFE_FUTURE_RESOLVE:
                continue
            out.append(Finding(
                "GL06", fi.path, n.lineno,
                f"thread-context code resolves a future via "
                f".{attr}() — an asyncio future must be resolved on "
                f"its loop (call_soon_threadsafe); if "
                f"{recv or 'this'!s} is a concurrent.futures.Future "
                f"handoff, declare it in "
                f"tables.THREADSAFE_FUTURE_RESOLVE{via}"))
        elif attr == "cancel" and ("task" in (recv or "").lower()):
            out.append(Finding(
                "GL06", fi.path, n.lineno,
                f"thread-context code cancels {recv} — task.cancel() "
                f"is loop-affine; route it through "
                f"loop.call_soon_threadsafe{via}"))
    return out


def _loop_findings(g: ctxgraph.ContextGraph,
                   fi: ctxgraph.FuncInfo) -> list[Finding]:
    out = []
    chain = g.chain(fi.qual, ctxgraph.LOOP)
    via = f" (loop-reachable via {chain})" if chain else ""
    exempt = _wrapper_exempt_ids(fi)
    for n in fi.body_walk():
        if not isinstance(n, ast.Call) or id(n) in exempt:
            continue
        name = dotted(n.func)
        msg = None
        if name == "time.sleep":
            msg = "time.sleep blocks the event loop"
        elif name in _BLOCKING_SUBPROCESS:
            msg = f"{name} blocks until the child exits"
        elif isinstance(n.func, ast.Attribute):
            attr = n.func.attr
            nargs = len(n.args) + len(n.keywords)
            if attr == "result":
                msg = ".result() blocks the loop on a concurrent " \
                      "future"
            elif attr in ("join", "communicate") and nargs == 0:
                msg = f".{attr}() with no arguments is a blocking " \
                      "thread/process primitive"
        if msg is not None:
            out.append(Finding(
                "GL06", fi.path, n.lineno,
                f"sync function reachable from loop context blocks: "
                f"{msg} — move it off-loop (asyncio.to_thread) or "
                f"split the thread/loop paths{via}"))
    return out


def check(idx: RepoIndex) -> list[Finding]:
    g = ctxgraph.build(idx)
    out: list[Finding] = []
    # stale declarative entries explain themselves (full-tree runs
    # only — a narrowed scan sees too little to call a row dead)
    for table_name in (("CTX_THREAD_ENTRY", "CTX_LOOP_ENTRY",
                        "THREADSAFE_FUTURE_RESOLVE")
                       if getattr(idx, "full_tree", True) else ()):
        table = getattr(tables, table_name)
        for qual, reason in table.items():
            path = qual.split("::")[0]
            if path in idx.code and qual not in g.funcs:
                out.append(Finding(
                    "GL06", path, 1,
                    f"stale tables.{table_name} entry {qual!r} "
                    f"(reason was: {reason}) — the function no longer "
                    f"exists; delete the entry"))
    for qual, fi in g.funcs.items():
        if fi.path not in idx.code:
            continue
        if qual in g.thread and not fi.is_async:
            out.extend(_thread_findings(g, fi))
        if qual in g.loop and not fi.is_async and qual not in g.thread:
            # both-context helpers are GL09's shared-state territory;
            # flagging their blocking calls as loop bugs would indict
            # the thread half too (declared, not inferred)
            out.extend(_loop_findings(g, fi))
    return out
