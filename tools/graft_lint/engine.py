"""graft-lint engine: file index, findings, pragma plane.

The engine parses every file ONCE (``ast`` tree + ``tokenize`` comment
stream) and hands checkers a :class:`RepoIndex`; checkers return
:class:`Finding` lists and never touch the filesystem themselves, so
the whole suite stays one pass over the tree (<30s is the ci.sh
stage-0 budget; in practice it is ~2s on this host).

Suppressions ride tokenize COMMENT tokens, not regex over lines — a
pragma spelled inside a string literal (the lint test fixtures hold
exactly those) is data, not a suppression.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path

#: pragma grammar: ``# graft-lint: disable=GL01[,GL03] -- reason``
_PRAGMA_RE = re.compile(
    r"#\s*graft-lint:\s*disable=(?P<codes>[A-Za-z0-9,\s]+?)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$")
_CODE_RE = re.compile(r"^GL\d\d$")


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str        # "GL01".."GL05", "GL00" for pragma-plane defects
    path: str        # repo-relative, posix separators
    line: int        # 1-based
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def sort_key(self):
        return (self.path, self.line, self.code, self.message)


@dataclasses.dataclass
class Pragma:
    line: int
    codes: frozenset  # of "GLxx"
    reason: str | None
    own_line: bool    # a full-line comment (suppresses the NEXT line too)


class SourceFile:
    """One parsed python file: tree + comment-derived pragma map."""

    def __init__(self, relpath: str, text: str):
        self.path = relpath
        self.text = text
        self.tree: ast.Module | None = None
        self.parse_error: str | None = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:  # surfaced as a finding by the engine
            self.parse_error = str(e)
        self.pragmas: list[Pragma] = []
        self._suppressed: dict[int, set] = {}  # line -> codes
        self._scan_pragmas()

    def _scan_pragmas(self) -> None:
        try:
            toks = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if m is None:
                continue
            codes = frozenset(c.strip() for c in
                              m.group("codes").split(",") if c.strip())
            own_line = tok.string.strip() == tok.line.strip()
            self.pragmas.append(Pragma(tok.start[0], codes,
                                       m.group("reason"), own_line))
        for p in self.pragmas:
            if p.reason is None or not all(_CODE_RE.match(c)
                                           for c in p.codes):
                continue  # malformed pragmas never suppress (GL00 below)
            self._suppressed.setdefault(p.line, set()).update(p.codes)
            if p.own_line:
                self._suppressed.setdefault(p.line + 1,
                                            set()).update(p.codes)

    def suppressed(self, code: str, line: int) -> bool:
        return code in self._suppressed.get(line, ())


class RepoIndex:
    """Parsed view of the tree.  ``code`` files get the full checker
    battery; ``test`` files only the pragma plane + GL05's reference
    scan (a test asserting a family name that does not exist pins
    nothing); ``docs`` are raw text for GL02/GL05 drift checks."""

    def __init__(self, root: Path):
        self.root = root
        self.code: dict[str, SourceFile] = {}
        self.tests: dict[str, SourceFile] = {}
        self.docs: dict[str, str] = {}

    # -- construction ------------------------------------------------------

    CODE_GLOBS = ("glusterfs_tpu/**/*.py", "tools/**/*.py", "bench.py",
                  "__graft_entry__.py")
    TEST_GLOBS = ("tests/**/*.py",)
    DOC_GLOBS = ("docs/*.md",)

    @classmethod
    def load(cls, root: Path, only: list[str] | None = None) -> "RepoIndex":
        idx = cls(root)
        #: narrowed runs skip cross-file STALE-entry checks: deciding
        #: that a table row is dead needs the whole tree in view (a
        #: lock defined in an unscanned file must not read as gone)
        idx.full_tree = only is None

        def want(rel: str) -> bool:
            if "__pycache__" in rel:
                return False
            return only is None or any(
                rel == o or rel.startswith(o.rstrip("/") + "/")
                for o in only)

        for pat in cls.CODE_GLOBS:
            for p in sorted(root.glob(pat)):
                rel = p.relative_to(root).as_posix()
                if p.is_file() and want(rel):
                    idx.code[rel] = SourceFile(
                        rel, p.read_text(encoding="utf-8"))
        for pat in cls.TEST_GLOBS:
            for p in sorted(root.glob(pat)):
                rel = p.relative_to(root).as_posix()
                if p.is_file() and want(rel):
                    idx.tests[rel] = SourceFile(
                        rel, p.read_text(encoding="utf-8"))
        if only is None:  # doc drift checks are whole-tree only
            for pat in cls.DOC_GLOBS:
                for p in sorted(root.glob(pat)):
                    rel = p.relative_to(root).as_posix()
                    if p.is_file():
                        idx.docs[rel] = p.read_text(encoding="utf-8")
        return idx

    # -- checker conveniences ----------------------------------------------

    def file(self, relpath: str) -> SourceFile | None:
        return self.code.get(relpath) or self.tests.get(relpath)

    def all_py(self) -> dict[str, SourceFile]:
        out = dict(self.code)
        out.update(self.tests)
        return out


def pragma_findings(idx: RepoIndex) -> list[Finding]:
    """GL00 — the pragma plane checks itself: a suppression without a
    reason, or with a malformed checker id, is a finding (and never
    suppresses anything)."""
    out = []
    for sf in idx.all_py().values():
        for p in sf.pragmas:
            bad = [c for c in p.codes if not _CODE_RE.match(c)]
            if bad:
                out.append(Finding(
                    "GL00", sf.path, p.line,
                    f"malformed graft-lint pragma: {','.join(bad)!r} is "
                    "not a checker id (GLxx)"))
            if p.reason is None:
                out.append(Finding(
                    "GL00", sf.path, p.line,
                    "suppression without a reason: write "
                    "'# graft-lint: disable=GLxx -- <why this site is "
                    "exempt>'"))
    return out


class NoFilesMatched(Exception):
    """A narrowed run whose paths select nothing must not report clean."""


def run(root: Path, only: list[str] | None = None,
        timings: dict | None = None) -> list[Finding]:
    """Parse the tree, run every checker, apply suppressions.  Pass a
    dict as ``timings`` to receive per-checker wall seconds (the ci.sh
    archived-json surface that makes a slow checker visible before it
    eats the 30s stage-0 budget)."""
    import time
    from . import all_checkers

    t0 = time.perf_counter()
    idx = RepoIndex.load(root, only)
    if only is not None and not idx.code and not idx.tests:
        raise NoFilesMatched(
            f"no scanned files match {only!r} — a typo'd path must not "
            "read as a clean tree")
    if timings is not None:
        timings["parse"] = round(time.perf_counter() - t0, 3)
    findings: list[Finding] = []
    for sf in idx.all_py().values():
        if sf.parse_error is not None:
            findings.append(Finding("GL00", sf.path, 1,
                                    f"does not parse: {sf.parse_error}"))
    findings.extend(pragma_findings(idx))
    for name, check in all_checkers():
        t0 = time.perf_counter()
        findings.extend(check(idx))
        if timings is not None:
            timings[name] = round(time.perf_counter() - t0, 3)
    kept = [f for f in findings
            if f.code == "GL00"
            or not _is_suppressed(idx, f)]
    return sorted(kept, key=Finding.sort_key)


def _is_suppressed(idx: RepoIndex, f: Finding) -> bool:
    sf = idx.file(f.path)
    return sf is not None and sf.suppressed(f.code, f.line)
