"""graft-lint runner.

Usage::

    python tools/graft_lint/run.py [--json] [paths...]

Exit codes: 0 clean, 1 findings, 2 internal error.  ``paths`` narrows
the scan to the given repo-relative files/directories (cross-file
checks that need files outside the narrowed set skip themselves);
default is the whole tree.  ``--json`` prints a machine-readable
finding list (the ci.sh stage-0 archive format).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="graft-lint")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files/dirs to narrow the scan")
    args = ap.parse_args(argv)

    if str(REPO_ROOT) not in sys.path:
        sys.path.insert(0, str(REPO_ROOT))
    from tools.graft_lint import engine

    t0 = time.monotonic()
    try:
        findings = engine.run(REPO_ROOT, args.paths or None)
    except engine.NoFilesMatched as e:
        print(f"graft-lint: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # noqa: BLE001 - runner must not masquerade
        print(f"graft-lint: internal error: {e!r}", file=sys.stderr)
        return 2
    dt = time.monotonic() - t0
    if args.json:
        print(json.dumps({
            "findings": [vars(f) for f in findings],
            "count": len(findings),
            "seconds": round(dt, 2),
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"graft-lint: {len(findings)} finding(s) in {dt:.1f}s")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
