"""graft-lint runner.

Usage::

    python tools/graft_lint/run.py [--json] [--changed] [paths...]
    python -m tools.graft_lint    [--json] [--changed] [paths...]

Exit codes: 0 clean, 1 findings, 2 internal error.  ``paths`` narrows
the scan to the given repo-relative files/directories (cross-file
checks that need files outside the narrowed set skip themselves);
default is the whole tree.  ``--changed`` narrows to the files git
reports as modified/staged/untracked plus their cross-file table
anchors — the fast pre-commit path (a change to the lint suite or a
table anchor falls back to the full tree, because those files feed
every checker).  ``--json`` prints a machine-readable finding list
with per-checker timings (the ci.sh stage-0 archive format).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

#: where the graft_lint PACKAGE lives — always importable from here,
#: independent of what tree is being scanned
_PKG_ROOT = Path(__file__).resolve().parents[2]
#: GRAFT_LINT_ROOT points the SCAN at another tree (the --changed
#: test fixtures build throwaway git repos); default is this repo
REPO_ROOT = Path(os.environ.get("GRAFT_LINT_ROOT")
                 or _PKG_ROOT).resolve()

#: files every checker (or its table evaluation) reads — a change here
#: can produce findings anywhere, so --changed escalates to full tree
FULL_TREE_ANCHORS = ("tools/graft_lint/", "glusterfs_tpu/core/fops.py",
                     "glusterfs_tpu/mgmt/volgen.py",
                     "glusterfs_tpu/core/metrics.py")

#: cross-file anchors added to every non-empty --changed scan so GL01/
#: GL02/GL05 have their vocabulary/option/registry ground truth
CHANGED_DEPS = ("glusterfs_tpu/core/fops.py",
                "glusterfs_tpu/mgmt/volgen.py",
                "glusterfs_tpu/core/metrics.py")


def _git_changed() -> list[str] | None:
    """Changed scan files (unstaged + staged + untracked), or None for
    'escalate to the full tree'."""
    def lines(*args: str) -> list[str]:
        res = subprocess.run(["git", *args], cwd=REPO_ROOT,
                             capture_output=True, text=True, timeout=30)
        if res.returncode != 0:
            raise RuntimeError(res.stderr.strip() or "git failed")
        return [ln.strip() for ln in res.stdout.splitlines()
                if ln.strip()]

    changed = set(lines("diff", "--name-only"))
    changed |= set(lines("diff", "--name-only", "--cached"))
    changed |= set(lines("ls-files", "--others", "--exclude-standard"))
    for c in changed:
        if any(c == a or c.startswith(a) for a in FULL_TREE_ANCHORS):
            return None  # suite/anchor change: findings can be anywhere
    scannable = [c for c in changed
                 if c.endswith(".py") and
                 (c.startswith(("glusterfs_tpu/", "tools/", "tests/"))
                  or c in ("bench.py", "__graft_entry__.py"))]
    return sorted(set(scannable) | set(CHANGED_DEPS)) if scannable \
        else []


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="graft-lint")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--changed", action="store_true",
                    help="lint only git-changed files plus their "
                         "table anchors (fast pre-commit path)")
    ap.add_argument("paths", nargs="*",
                    help="repo-relative files/dirs to narrow the scan")
    args = ap.parse_args(argv)

    if str(_PKG_ROOT) not in sys.path:
        sys.path.insert(0, str(_PKG_ROOT))
    from tools.graft_lint import engine

    only = args.paths or None
    if args.changed:
        if only is not None:
            print("graft-lint: --changed and explicit paths are "
                  "mutually exclusive", file=sys.stderr)
            return 2
        try:
            only = _git_changed()
        except Exception as e:  # noqa: BLE001 - degrade to full tree
            print(f"graft-lint: --changed: git unavailable ({e}); "
                  "scanning the full tree", file=sys.stderr)
            only = None
        if only == []:
            if args.json:
                print(json.dumps({"findings": [], "count": 0,
                                  "seconds": 0.0, "changed": [],
                                  "checker_seconds": {}}, indent=2))
            else:
                print("graft-lint: no changed files — clean")
            return 0

    t0 = time.monotonic()
    timings: dict = {}
    try:
        findings = engine.run(REPO_ROOT, only, timings=timings)
    except engine.NoFilesMatched as e:
        print(f"graft-lint: {e}", file=sys.stderr)
        return 2
    except Exception as e:  # noqa: BLE001 - runner must not masquerade
        print(f"graft-lint: internal error: {e!r}", file=sys.stderr)
        return 2
    dt = time.monotonic() - t0
    if args.json:
        payload = {
            "findings": [vars(f) for f in findings],
            "count": len(findings),
            "seconds": round(dt, 2),
            "checker_seconds": timings,
        }
        if args.changed:
            payload["changed"] = only if only is not None else \
                "full tree (lint-suite or table-anchor change)"
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.render())
        slowest = max(timings.items(), key=lambda kv: kv[1],
                      default=None)
        slow = f", slowest {slowest[0]} {slowest[1]:.1f}s" \
            if slowest else ""
        print(f"graft-lint: {len(findings)} finding(s) in "
              f"{dt:.1f}s{slow}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
