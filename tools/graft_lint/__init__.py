"""graft-lint — cross-file invariant checker for the fop, option,
async, errno and metrics planes.

Every checker here is grounded in a defect class this repo has already
paid for in review time (docs/static_analysis.md carries the catalog
with the historical bug behind each id):

* **GL01** fop-vocabulary completeness: read/write classification,
  changelog journaling, io-threads priority, brick-side fence parity
  (worm / bit-rot-stub / locks / read-only / barrier), and the
  idempotent-retry allowlist staying read-class.
* **GL02** option-plane consistency: dotted option-key reads vs
  volgen's OPTION_MAP, OPTION_MIN_OPVERSION ⊆ OPTION_MAP,
  docs/volume_options.md regenerate-and-diff, SETVOLUME capability
  advertisement vs client check sites.
* **GL03** async discipline: blocking calls inside ``async def``.
* **GL04** errno discipline: ``.errno`` where ``FopError.err`` is the
  contract, bare integer errno literals.
* **GL05** metrics-family discipline: every ``gftpu_*`` family
  registered exactly once, label-key consistency, references in
  tests/docs resolve to registered families.

Suppression: ``# graft-lint: disable=GLxx -- <reason>`` on the finding
line (or the full-line comment directly above it).  A suppression
WITHOUT a reason is itself a finding (GL00) — the pragma plane is
checked like everything else.  There are no file-level excludes.

Pure stdlib (``ast`` + ``tokenize``); the only import of repo code is
GL02's regenerate-and-diff of docs/volume_options.md, which calls
``mgmt.volgen.options_doc()`` because the doc IS that function's
output.
"""

from __future__ import annotations

__all__ = ["all_checkers"]


def all_checkers():
    """The checker registry, id-ordered (GL00 runs in the engine)."""
    from . import gl01_fops, gl02_options, gl03_async, gl04_errno, \
        gl05_metrics

    return [gl01_fops.check, gl02_options.check, gl03_async.check,
            gl04_errno.check, gl05_metrics.check]
