"""graft-lint — cross-file invariant checker for the fop, option,
async, errno and metrics planes.

Every checker here is grounded in a defect class this repo has already
paid for in review time (docs/static_analysis.md carries the catalog
with the historical bug behind each id):

* **GL01** fop-vocabulary completeness: read/write classification,
  changelog journaling, io-threads priority, brick-side fence parity
  (worm / bit-rot-stub / locks / read-only / barrier), and the
  idempotent-retry allowlist staying read-class.
* **GL02** option-plane consistency: dotted option-key reads vs
  volgen's OPTION_MAP, OPTION_MIN_OPVERSION ⊆ OPTION_MAP,
  docs/volume_options.md regenerate-and-diff, SETVOLUME capability
  advertisement vs client check sites.
* **GL03** async discipline: blocking calls inside ``async def``.
* **GL04** errno discipline: ``.errno`` where ``FopError.err`` is the
  contract, bare integer errno literals.
* **GL05** metrics-family discipline: every ``gftpu_*`` family
  registered exactly once, label-key consistency, references in
  tests/docs resolve to registered families.

The graft-race suite (GL06-GL09) adds flow- and context-sensitive
concurrency checks over a shared execution-context reachability
analysis (:mod:`ctxgraph` — thread entries from Thread targets /
executor submits / the declarative tables, loop entries from ``async
def`` and loop-callback registration, propagated through the call
graph):

* **GL06** loop/thread boundary discipline: thread-context code must
  reach the loop only through ``call_soon_threadsafe`` /
  ``run_coroutine_threadsafe``; loop-reachable sync code must not
  block on concurrent futures / sleeps / child processes.
* **GL07** lock discipline: no ``await`` (or known-lazy first-call
  compile) while holding a ``threading.Lock``; the per-class lock
  acquisition graph stays acyclic.
* **GL08** task/future lifecycle: every ``create_task`` result
  retained (weak-ref GC hazard), every created future resolved on all
  paths including exception edges.
* **GL09** shared-state ownership: attributes crossing the
  thread/loop boundary are lock-protected (machine-verified),
  immutable-after-start, or declared in ``tables.OWNERSHIP``.

Suppression: ``# graft-lint: disable=GLxx -- <reason>`` on the finding
line (or the full-line comment directly above it).  A suppression
WITHOUT a reason is itself a finding (GL00) — the pragma plane is
checked like everything else.  There are no file-level excludes.

Pure stdlib (``ast`` + ``tokenize``); the only import of repo code is
GL02's regenerate-and-diff of docs/volume_options.md, which calls
``mgmt.volgen.options_doc()`` because the doc IS that function's
output.
"""

from __future__ import annotations

__all__ = ["all_checkers"]


def all_checkers():
    """The checker registry, id-ordered (GL00 runs in the engine):
    ``(checker id, callable)`` pairs so the runner can time each one
    (ci.sh archives per-checker seconds — a slow checker must be
    visible before it eats the 30s stage-0 budget)."""
    from . import gl01_fops, gl02_options, gl03_async, gl04_errno, \
        gl05_metrics, gl06_context, gl07_locks, gl08_lifecycle, \
        gl09_ownership

    return [("GL01", gl01_fops.check), ("GL02", gl02_options.check),
            ("GL03", gl03_async.check), ("GL04", gl04_errno.check),
            ("GL05", gl05_metrics.check), ("GL06", gl06_context.check),
            ("GL07", gl07_locks.check), ("GL08", gl08_lifecycle.check),
            ("GL09", gl09_ownership.check)]
