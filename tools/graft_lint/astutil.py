"""Small AST helpers shared by the checkers (stdlib ``ast`` only)."""

from __future__ import annotations

import ast


def dotted(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(func: ast.AST) -> str:
    """Last component of a call target: ``a.b.c`` -> ``c``, and — where
    :func:`dotted` gives up — the attribute name of chains rooted in a
    call (``get_event_loop().create_future`` -> ``create_future``)."""
    d = dotted(func)
    if d:
        return d.split(".")[-1]
    return func.attr if isinstance(func, ast.Attribute) else ""


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fop_name(node: ast.AST) -> str | None:
    """``Fop.WRITEV`` -> ``"writev"`` (the enum VALUE convention: every
    member's value is its lowercased name)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "Fop":
        return node.attr.lower()
    return None


class SetEvalError(Exception):
    pass


def eval_fop_set(node: ast.AST, env: dict[str, frozenset]) -> frozenset:
    """Evaluate a module-level fop-set expression to a frozenset of fop
    value strings.  Understands set literals of ``Fop.X``, names bound
    in ``env`` (e.g. WRITE_FOPS), ``frozenset(...)`` / ``set(...)``
    wrapping, and the ``| - &`` set operators — the shapes the fence
    and classification tables actually use."""
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out = set()
        for e in node.elts:
            f = fop_name(e)
            if f is None:
                s = const_str(e)
                if s is None:
                    raise SetEvalError(ast.dump(e))
                out.add(s)
            else:
                out.add(f)
        return frozenset(out)
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise SetEvalError(f"unknown name {node.id}")
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("frozenset", "set") and \
            len(node.args) == 1:
        return eval_fop_set(node.args[0], env)
    if isinstance(node, ast.BinOp):
        left = eval_fop_set(node.left, env)
        right = eval_fop_set(node.right, env)
        if isinstance(node.op, ast.BitOr):
            return left | right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.BitAnd):
            return left & right
        raise SetEvalError(f"operator {node.op}")
    raise SetEvalError(ast.dump(node)[:80])


def module_fop_sets(tree: ast.Module,
                    seed: dict[str, frozenset] | None = None
                    ) -> dict[str, frozenset]:
    """Walk module-level assignments in order, evaluating every
    fop-set-shaped one into an environment (barrier's ``_GATED``
    builds on io-threads-style prior names)."""
    env: dict[str, frozenset] = dict(seed or {})
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            continue
        tgt = stmt.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        try:
            env[tgt.id] = eval_fop_set(stmt.value, env)
        except SetEvalError:
            continue
    return env


def class_def(tree: ast.Module, name_suffix: str) -> ast.ClassDef | None:
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef) and \
                stmt.name.endswith(name_suffix):
            return stmt
    return None


def calls_in(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def str_keys(node: ast.Dict) -> list[str] | None:
    """All-literal-string keys of a dict literal, else None."""
    out = []
    for k in node.keys:
        s = const_str(k) if k is not None else None
        if s is None:
            return None
        out.append(s)
    return out
