"""GL04 — errno discipline.

Historical bugs: PR 11's linkto-marker gate compared ``e.errno`` on a
``FopError`` (the OSError alias is the WRONG field contract here —
``FopError.err`` is the codebase's op_errno), and PR 9 shipped a bare
``110`` where ``errno.ETIMEDOUT`` was meant.

Flagged:

* ``<var>.errno`` where ``<var>`` is bound by an ``except`` clause that
  names ``FopError`` (catching plain OSError keeps ``.errno``);
* ``FopError(<int literal>, ...)`` — raise with ``errno.<NAME>``;
* comparisons of an ``.err`` / ``.errno`` attribute against a bare
  integer literal (``e.err == 2`` reads as line noise; ``errno.ENOENT``
  reads as intent).
"""

from __future__ import annotations

import ast

from .astutil import dotted
from .engine import Finding, RepoIndex


def _names_fop_error(type_node: ast.AST | None) -> bool:
    if type_node is None:
        return False
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    return any(dotted(n).split(".")[-1] == "FopError" for n in nodes)


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list[Finding] = []
        self._fop_err_vars: list[str] = []  # stack of handler var names

    def visit_ExceptHandler(self, node):
        is_fop = _names_fop_error(node.type) and node.name is not None
        if is_fop:
            self._fop_err_vars.append(node.name)
        self.generic_visit(node)
        if is_fop:
            self._fop_err_vars.pop()

    def visit_Attribute(self, node):
        if node.attr == "errno" and isinstance(node.value, ast.Name) \
                and node.value.id in self._fop_err_vars:
            self.findings.append(Finding(
                "GL04", self.path, node.lineno,
                f"'{node.value.id}.errno' on a FopError — the "
                "codebase contract is '.err' (op_errno); .errno is "
                "the OSError alias and reads as the wrong plane"))
        self.generic_visit(node)

    def visit_Call(self, node):
        if dotted(node.func).split(".")[-1] == "FopError" and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, int) \
                    and not isinstance(a.value, bool) and a.value != 0:
                self.findings.append(Finding(
                    "GL04", self.path, node.lineno,
                    f"bare integer errno {a.value} in FopError(...) — "
                    "use errno.<NAME> so the intent survives review "
                    "(the PR-9 bare-110 class)"))
        self.generic_visit(node)

    def visit_Compare(self, node):
        sides = [node.left] + list(node.comparators)
        has_err_attr = any(
            isinstance(s, ast.Attribute) and s.attr in ("err", "errno")
            for s in sides)
        bad_int = next(
            (s for s in sides
             if isinstance(s, ast.Constant) and isinstance(s.value, int)
             and not isinstance(s.value, bool) and s.value > 0), None)
        if has_err_attr and bad_int is not None and all(
                isinstance(op, (ast.Eq, ast.NotEq, ast.In, ast.NotIn))
                for op in node.ops):
            self.findings.append(Finding(
                "GL04", self.path, node.lineno,
                f"errno attribute compared against bare integer "
                f"{bad_int.value} — use errno.<NAME>"))
        self.generic_visit(node)


def check(idx: RepoIndex) -> list[Finding]:
    out: list[Finding] = []
    for sf in idx.code.values():
        if sf.tree is None:
            continue
        v = _Visitor(sf.path)
        v.visit(sf.tree)
        out.extend(v.findings)
    return out
