"""graft-lint declarative tables — the editing surface for op-version 16.

Adding a fop, option key, or capability should mean editing DATA here
(plus the real code site), never checker logic.  Every exemption is a
``fop-or-key -> reason`` pair; the reason is rendered into findings
when a table drifts, so a stale entry explains itself.

Checker-facing contracts:

* GL01 reads ``READ_CLASS`` (the explicit non-mutating half of the fop
  vocabulary), ``CHANGELOG_EXEMPT``, ``IOT_SLOW_EXEMPT`` and
  ``FENCES`` (per brick-side gate layer: how its gate set is declared
  and which write fops it deliberately does not gate).
* GL02 reads ``OPTION_READ_EXEMPT`` (dotted ``.get()`` keys that look
  like volume options but are not), ``OPTION_KEY_PREFIXES`` (what
  counts as option-shaped) and ``CAPABILITIES`` (SETVOLUME reply key
  -> where the client must check it, or an exemption reason).
* GL05 reads ``NON_FAMILY_LITERALS`` (``gftpu_``-prefixed strings that
  are not metrics families).
"""

from __future__ import annotations

# --------------------------------------------------------------------------
# GL01 — fop vocabulary
# --------------------------------------------------------------------------

#: The non-mutating half of the vocabulary.  GL01 requires
#: READ_CLASS ∪ WRITE_FOPS == every Fop member, disjoint — a new fop
#: lands here or in core/fops.WRITE_FOPS, explicitly, or lint fails.
#: (flush/fsync/fsyncdir are durability ops over already-journaled
#: mutations; lock/lease fops are coordination; compound is a carrier
#: whose links classify individually.)
READ_CLASS = frozenset({
    "stat", "readlink", "open", "readv", "statfs", "flush", "fsync",
    "getxattr", "opendir", "fsyncdir", "access", "fstat", "lk",
    "lookup", "readdir", "inodelk", "finodelk", "entrylk", "fentrylk",
    "fgetxattr", "rchecksum", "readdirp", "ipc", "seek", "lease",
    "getactivelk", "setactivelk", "compound",
})

#: Write-class fops deliberately absent from changelog's E/D/M record
#: classes (features/changelog.py).
CHANGELOG_EXEMPT = {
    "xattrop": "internal version/dirty settle accounting — the EC/AFR "
               "transaction engines' bookkeeping, not a user mutation "
               "(the reference changelog excludes it too; user-visible "
               "xattr changes journal via setxattr/M)",
    "fxattrop": "fd twin of xattrop — same internal-settle exemption",
}

#: Write-class fops allowed to fall into io-threads' implicit slow
#: queue instead of an explicit FAST/NORMAL/LEAST/UNGATED class.
#: Empty on purpose: PR 13 classified the whole write vocabulary after
#: GL01 caught nine write fops (fallocate/discard/zerofill/put/
#: copy_file_range/removexattr/fremovexattr/icreate/namelink) silently
#: riding the slow queue, inverting them vs sibling writevs of the
#: same workload — the exact inversion the XORV comment warns about.
IOT_SLOW_EXEMPT: dict[str, str] = {}

#: Brick-side fence layers and their deliberate non-gates.
#: ``kind``: how GL01 discovers the gate set —
#:   "loop"    : a module-level ``for _f in <set-expr>: setattr(...)``
#:               (read-only, barrier);
#:   "methods" : explicitly defined write-fop methods whose body calls
#:               one of ``markers`` (or raises FopError) before
#:               winding (worm, locks, bit-rot-stub).
#: ``exempt`` : write-class fop -> reason it is NOT gated here.
_ENTRY_OPS_LOCKS = "namespace ops are serialized by entrylk/inodelk " \
    "domains (features/locks' other half), not posix byte-range locks"
_XATTR_OPS_LOCKS = "xattr mutations are not byte-range file content; " \
    "mandatory lock semantics cover data ranges only"
_ENTRY_OPS_BITROT = "quarantine fences object CONTENT; removing or " \
    "re-homing the object whole (unlink/rename/entry ops) is the " \
    "operator remedy and leaves nothing corrupt to serve"
_XATTR_OPS_BITROT = "scrub/heal bookkeeping (signatures, quarantine " \
    "marks, EC versions) rides xattrs and must flow through the stub"
_CREATE_OPS_WORM = "creating NEW entries is the WORM-allowed half of " \
    "write-once-read-many; only mutation of existing state is fenced"

FENCES = {
    "glusterfs_tpu/features/read_only.py": {
        "layer": "ReadOnlyLayer",
        "kind": "loop",
        "exempt": {},
    },
    "glusterfs_tpu/features/barrier.py": {
        "layer": "BarrierLayer",
        "kind": "loop",
        "exempt": {
            "xattrop": "the eager-window settle wave (xattrop post-op "
                       "+ compound unlock) must flow THROUGH an armed "
                       "barrier or the snapshot quiesce deadlocks on "
                       "its own contention upcalls (barrier.py module "
                       "comment; absent from the reference barrier "
                       "fop table too)",
            "fxattrop": "fd twin of xattrop — same settle-wave "
                        "exemption",
        },
    },
    "glusterfs_tpu/features/worm.py": {
        "layer": "WormLayer",
        "kind": "methods",
        "markers": ("_deny_file_level", "_on", "_file_level"),
        "exempt": {
            "mknod": _CREATE_OPS_WORM, "mkdir": _CREATE_OPS_WORM,
            "symlink": _CREATE_OPS_WORM, "create": _CREATE_OPS_WORM,
            "icreate": _CREATE_OPS_WORM,
            "namelink": "no storage/posix implementation yet "
                        "(EOPNOTSUPP at the leaf) — fence it like "
                        "link the day it lands",
            "rmdir": "directories carry no WORM state (worm.c fences "
                     "file bodies; an empty dir has no retained data)",
            "xattrop": "internal EC/AFR accounting must flow (same "
                       "settle-wave argument as the barrier exemption)",
            "fxattrop": "fd twin of xattrop",
        },
    },
    "glusterfs_tpu/features/locks.py": {
        "layer": "LocksLayer",
        "kind": "methods",
        "markers": ("_mandatory_check",),
        "exempt": {
            "mknod": _ENTRY_OPS_LOCKS, "mkdir": _ENTRY_OPS_LOCKS,
            "unlink": _ENTRY_OPS_LOCKS, "rmdir": _ENTRY_OPS_LOCKS,
            "symlink": _ENTRY_OPS_LOCKS, "rename": _ENTRY_OPS_LOCKS,
            "link": _ENTRY_OPS_LOCKS, "create": _ENTRY_OPS_LOCKS,
            "icreate": _ENTRY_OPS_LOCKS,
            "namelink": "no storage/posix implementation yet "
                        "(EOPNOTSUPP at the leaf); an entry op anyway "
                        "— the entrylk domain is its fence",
            "setxattr": _XATTR_OPS_LOCKS,
            "removexattr": _XATTR_OPS_LOCKS,
            "fsetxattr": _XATTR_OPS_LOCKS,
            "fremovexattr": _XATTR_OPS_LOCKS,
            "xattrop": _XATTR_OPS_LOCKS, "fxattrop": _XATTR_OPS_LOCKS,
            "setattr": "inode metadata (mode/times/owner) is not "
                       "byte-range content; reference posix-locks has "
                       "no pl_setattr mandatory hook",
            "fsetattr": "fd twin of setattr",
        },
    },
    "glusterfs_tpu/features/bit_rot_stub.py": {
        "layer": "BitRotStubLayer",
        "kind": "methods",
        "markers": ("_deny",),
        "exempt": {
            "mknod": _ENTRY_OPS_BITROT, "mkdir": _ENTRY_OPS_BITROT,
            "unlink": _ENTRY_OPS_BITROT, "rmdir": _ENTRY_OPS_BITROT,
            "symlink": _ENTRY_OPS_BITROT, "rename": _ENTRY_OPS_BITROT,
            "link": _ENTRY_OPS_BITROT, "create": _ENTRY_OPS_BITROT,
            "icreate": _ENTRY_OPS_BITROT,
            "namelink": "no storage/posix implementation yet "
                        "(EOPNOTSUPP at the leaf); an entry op anyway",
            "setxattr": _XATTR_OPS_BITROT,
            "removexattr": _XATTR_OPS_BITROT,
            "fsetxattr": _XATTR_OPS_BITROT,
            "fremovexattr": _XATTR_OPS_BITROT,
            "setattr": "metadata does not touch the corrupt content "
                       "the quarantine preserves for the scrubber",
            "fsetattr": "fd twin of setattr",
        },
    },
}

# --------------------------------------------------------------------------
# GL02 — option plane
# --------------------------------------------------------------------------

#: What an option-shaped dotted key looks like (left of the first dot).
#: Dotted ``.get()`` reads under these prefixes must resolve to
#: volgen's OPTION_MAP.
OPTION_KEY_PREFIXES = (
    "auth", "bitrot", "changelog", "client", "cluster", "config",
    "ctime", "debug", "diagnostics", "disperse", "features", "gateway",
    "locks", "network", "performance", "rebalance", "server", "ssl",
    "storage", "transport",
)

#: Dotted keys that match the prefixes but are NOT volume-set options.
OPTION_READ_EXEMPT: dict[str, str] = {}

#: SETVOLUME reply capabilities (protocol/server handshake reply keys
#: beyond volume/ok/error).  Value: the ``res.get("<cap>")`` check the
#: client must have, or ("exempt", reason).
CAPABILITIES = {
    "compound": "checked",
    "trace": "checked",
    "deadline": "checked",
    "xorv": "checked",
    "leases": "checked",
    "sg": ("exempt",
           "requester-driven: the client ASKS via the sg-replies cred "
           "and must decode sg frames iff it asked; the reply key is "
           "the server's per-connection grant, consumed by the "
           "server's own encoder (conn.sg) — there is no client-side "
           "branch to take on it"),
    "shm": ("exempt",
            "advert, not a flag: the reply value is a dict (boot-id + "
            "side-channel addr + one-shot token) consumed by "
            "client._shm_arm via res.get('shm'); the armed state "
            "lives in _peer_shm after the fd exchange + __shm_ok__ "
            "confirm, not in a res.get branch"),
}

# --------------------------------------------------------------------------
# GL06-GL09 — graft-race concurrency plane (ctxgraph)
# --------------------------------------------------------------------------

#: Extra thread-context entry points the syntax cannot see (dynamic
#: dispatch, callables stored then spawned elsewhere).  Key:
#: ``path::Scope.func``; value: why this runs on a thread.
CTX_THREAD_ENTRY: dict[str, str] = {}

#: Extra loop-context entry points (callables registered with a loop
#: through an indirection ctxgraph cannot follow).
CTX_LOOP_ENTRY: dict[str, str] = {}

#: Functions whose ``set_result``/``set_exception`` from thread
#: context resolve a **concurrent.futures.Future** (thread-safe by
#: contract) rather than an asyncio future.  Key: ``path::Scope.func``.
THREADSAFE_FUTURE_RESOLVE: dict[str, str] = {}

#: Callables that trace/compile on FIRST call (jax.jit laziness):
#: calling one inside a ``with <threading.Lock>`` body turns the lock
#: into a seconds-long process-wide stall (GL07).  Key: dotted-name
#: suffix as written at call sites; value: what makes it lazy.
_MESH_JIT = "lru-cached jax.jit factory — the returned callable " \
    "traces + compiles the whole mesh program at first call per shape"
KNOWN_LAZY: dict[str, str] = {
    "sharded_step_fn": _MESH_JIT + " (parallel/mesh_codec.py)",
    "_encode_fn": _MESH_JIT,
    "_parity_fn": _MESH_JIT,
    "_decode_fn": _MESH_JIT + " (one program per surviving mask)",
    "_ring_decode_fn": _MESH_JIT + " (parallel/ring_codec.py)",
    "jax.jit":
        "jit construction is cheap but the returned callable compiles "
        "at first call; building it under a lock invites calling it "
        "there too",
}

#: Sites that hold a lock across a known-lazy call ON PURPOSE
#: (serializing the first compile IS the design, the PR-8 second-pass
#: fix).  Key: ``path::Scope.func::lazy-name``; value: reason.
_BUILD_LOCK_WHY = "deliberate (PR 8, second review pass): jax.jit is " \
    "LAZY, so the serialization _BUILD_LOCK exists for — two flush " \
    "workers racing an encode/decode first trace+compile (observed " \
    "once as a pybind11 instance-allocation failure under e2e load) " \
    "— only happens when the lock SPANS the jitted call; holding it " \
    "costs little because the backend serializes on-device execution " \
    "anyway and shape bucketing bounds how often a call compiles"
LAZY_UNDER_LOCK_OK: dict[str, str] = {
    "glusterfs_tpu/parallel/mesh_codec.py::run_step::sharded_step_fn":
        _BUILD_LOCK_WHY,
    "glusterfs_tpu/parallel/mesh_codec.py::sharded_encode::_encode_fn":
        _BUILD_LOCK_WHY,
    "glusterfs_tpu/parallel/mesh_codec.py::sharded_encode::_parity_fn":
        _BUILD_LOCK_WHY + " (systematic branch)",
    "glusterfs_tpu/parallel/mesh_codec.py::sharded_parity::_parity_fn":
        _BUILD_LOCK_WHY,
    "glusterfs_tpu/parallel/mesh_codec.py::sharded_decode::_decode_fn":
        _BUILD_LOCK_WHY,
    "glusterfs_tpu/parallel/ring_codec.py::ring_decode::_ring_decode_fn":
        _BUILD_LOCK_WHY,
}

#: Cross-context instance attributes (written in one of loop/thread
#: context, touched in the other) that are neither machine-verifiably
#: lock-protected nor immutable-after-start.  Key:
#: ``path::Class.attr``; value: (classification, reason) with
#: classification one of "lock-protected" (a design the lexical check
#: cannot see), "immutable-after-start", "threadsafe-handoff"
#: (queue/event/GIL-atomic flag).  New cross-context state is a
#: reviewed DATA edit here — the graft-lint precedent (GL09).
OWNERSHIP: dict[str, tuple[str, str]] = {
    "glusterfs_tpu/features/changelog.py::ChangelogLayer._dir": (
        "immutable-after-start",
        "set once in async init() before the brick serves a single "
        "fop; the history-scan closure (asyncio.to_thread) and the "
        "journal writers only ever read it"),
    "glusterfs_tpu/mount/fuse_bridge.py::FuseBridge.dev_fd": (
        "threadsafe-handoff",
        "GIL-atomic int sentinel: mount() publishes the fd BEFORE "
        "spawning the reader/writer split threads, and the only "
        "cross-context write afterwards is _teardown's -1, which the "
        "threads poll to stand down (each thread OWNS its actual fd: "
        "_rfd/_wfd, closed by the owner) — the documented split-plane "
        "teardown contract (docs/event_threads.md)"),
    "glusterfs_tpu/ops/batch.py::BatchingCodec._cpu": (
        "lock-protected",
        "double-checked lazy build under self._lock (the graft-race "
        "fix): the unlocked fast-path read can see a stale None and "
        "then serializes on the lock; it can never see a partially "
        "built codec because the GIL publishes the assignment whole"),
    "glusterfs_tpu/ops/batch.py::BatchingCodec._mesh": (
        "threadsafe-handoff",
        "written exactly once by the warm thread BEFORE _mesh_state "
        "flips to 'ready' (program-order publication the GIL makes "
        "visible); loop readers gate every access on _mesh_state"),
    "glusterfs_tpu/ops/batch.py::BatchingCodec._mesh_state": (
        "threadsafe-handoff",
        "single-writer state machine (off -> warming -> ready/"
        "unavailable) advanced only by the warm thread via GIL-atomic "
        "str assignment; loop reads tolerate staleness BY DESIGN — "
        "'warming' routes flushes to the measured ladder fallback, "
        "which is the codec's whole wedge-safety story"),
}

# --------------------------------------------------------------------------
# GL05 — metrics plane
# --------------------------------------------------------------------------

#: ``gftpu_``-prefixed string literals that are not metrics families
#: and that the checker cannot recognize structurally
#: (``ContextVar("gftpu_...")`` names are already auto-exempt).
NON_FAMILY_LITERALS: dict[str, str] = {}
