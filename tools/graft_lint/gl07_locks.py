"""GL07 — threading-lock discipline (graft-race).

Historical bug: PR 8's ``_BUILD_LOCK`` was released *before* the lazy
jit call it was supposed to serialize — jax.jit traces/compiles at the
first CALL, so the criticial region was empty; the review pass had to
re-derive lock extent by hand.  The inverse hazard is as real: an
``await`` (or a multi-second lazy first-compile) while HOLDING a
``threading.Lock`` parks every other thread — and on the hybrid plane
one of those threads may be running the event loop's only executor.

Three checks, over locks discovered structurally (``self.x =
threading.Lock()`` / module-level ``X = threading.Lock()``, RLock and
Condition included, any import alias):

* **await-under-lock** — an ``await`` lexically inside a ``with
  <threading lock>:`` body.  A threading lock held across a suspension
  point outlives its task's scheduling slice: every OTHER thread
  touching the lock blocks for as long as the loop takes to resume the
  coroutine, and a second task acquiring the same lock on the SAME
  loop deadlocks it outright.  (``asyncio.Lock`` is the loop-side
  primitive.)
* **known-lazy-under-lock** — a call to a :data:`tables.KNOWN_LAZY`
  callable inside a lock body: these compile/trace on first call
  (seconds of GIL-holding work), which turns the lock into a
  process-wide stall.  Sites that *deliberately* serialize the compile
  (the PR-8 fix holds _BUILD_LOCK across the jitted call on purpose)
  declare themselves in :data:`tables.LAZY_UNDER_LOCK_OK` with the
  reason.
* **lock-order cycles** — the per-class/per-module acquisition graph
  (lock A held while B is acquired, through same-file direct calls)
  must stay acyclic; an A->B / B->A pair is a deadlock waiting for two
  threads to interleave.
"""

from __future__ import annotations

import ast

from . import ctxgraph, tables
from .astutil import call_name, dotted
from .engine import Finding, RepoIndex

_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def _lock_defs(idx: RepoIndex) -> dict[str, set[str]]:
    """path -> lock names DEFINED there ('self._lock' attrs and bare
    module-level names assigned a threading Lock/RLock/Condition)."""
    out: dict[str, set[str]] = {}
    for path, sf in idx.code.items():
        if sf.tree is None:
            continue
        names: set[str] = set()
        for n in ast.walk(sf.tree):
            if not isinstance(n, (ast.Assign, ast.AnnAssign)):
                continue
            value = n.value
            if not (isinstance(value, ast.Call)
                    and isinstance(value.func, (ast.Attribute, ast.Name))
                    and dotted(value.func).split(".")[-1] in _LOCK_CTORS
                    and dotted(value.func) != "asyncio.Lock"):
                continue
            targets = n.targets if isinstance(n, ast.Assign) \
                else [n.target]
            for t in targets:
                d = dotted(t)
                if d:
                    names.add(d)  # "self._lock" or "_BUILD_LOCK"
        if names:
            out[path] = names
    return out


def _lock_env(idx: RepoIndex) -> dict[str, dict[str, tuple[str, str]]]:
    """path -> {name-as-written-at-a-with-site: (defining path, lock
    name)}.  Local definitions plus IMPORTED module-level locks — the
    ring_codec plane acquires mesh_codec._BUILD_LOCK across files, and
    a file-local view would neither see that acquisition nor order it
    against the owner's."""
    from . import ctxgraph as _cg

    defs = _lock_defs(idx)
    mod_to_path = {_cg._module_of(p): p for p in idx.code}
    env: dict[str, dict[str, tuple[str, str]]] = {}
    for path, sf in idx.code.items():
        if sf.tree is None:
            continue
        m: dict[str, tuple[str, str]] = {}
        for name in defs.get(path, ()):
            m[name] = (path, name)
        pkg_parts = path.split("/")[:-1]
        for stmt in ast.walk(sf.tree):
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    tgt = mod_to_path.get(alias.name)
                    if tgt is None:
                        continue
                    asname = alias.asname or alias.name.split(".")[0]
                    if alias.asname is None and "." in alias.name:
                        continue  # a.b.c without asname: written fully
                    for lk in defs.get(tgt, ()):
                        if "." not in lk:
                            m[f"{asname}.{lk}"] = (tgt, lk)
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level == 0:
                    mod = stmt.module
                else:
                    base = pkg_parts[: len(pkg_parts)
                                     - (stmt.level - 1)]
                    mod = ".".join(
                        base + ([stmt.module] if stmt.module else []))
                if not mod:
                    continue
                for alias in stmt.names:
                    nm = alias.asname or alias.name
                    sub = mod_to_path.get(f"{mod}.{alias.name}")
                    if sub is not None:  # imported a MODULE
                        for lk in defs.get(sub, ()):
                            if "." not in lk:
                                m[f"{nm}.{lk}"] = (sub, lk)
                    else:  # maybe imported the lock object itself
                        tgt = mod_to_path.get(mod)
                        if tgt is not None and \
                                alias.name in defs.get(tgt, ()):
                            m[nm] = (tgt, alias.name)
        if m:
            env[path] = m
    return env


def _shallow_walk(body: list[ast.AST]):
    """Walk statements without descending into nested function/lambda
    bodies — code merely DEFINED under a lock does not run under it."""
    stack = list(body)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _with_lock_items(fn_node: ast.AST, locks: set[str]):
    """(with_node, lock_name, body) for lock acquisitions in this
    function's own body (nested defs are their own FuncInfos)."""
    body = getattr(fn_node, "body", [])
    if not isinstance(body, list):  # lambda
        return
    for n in _shallow_walk(body):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                d = dotted(item.context_expr)
                if d in locks:
                    yield n, d, n.body


def check(idx: RepoIndex) -> list[Finding]:
    g = ctxgraph.build(idx)
    lock_env = _lock_env(idx)
    out: list[Finding] = []

    #: declared lazy-under-lock sites actually observed in this run
    #: (path::scope::lazy) — a declaration whose site no longer holds
    #: the lock across the lazy call is stale, so the table verifies
    #: the PR-8 lock-extent contract instead of merely excusing it
    seen_declared: set[str] = set()

    # per-function direct-acquire sets + call edges for the
    # acquisition graph (lock ids are canonical (defining-path, name)
    # pairs, so a cross-file acquisition orders against the owner's)
    acquires: dict[str, set[tuple[str, str]]] = {}
    for qual, fi in g.funcs.items():
        locks = lock_env.get(fi.path, {})
        if not locks:
            continue
        mine = set()
        for _, lock, _ in _with_lock_items(fi.node, locks):
            mine.add(locks[lock])
        if mine:
            acquires[qual] = mine

    # transitive acquire sets through resolved direct calls (bounded
    # fixpoint — the graph is tiny)
    trans: dict[str, set[tuple[str, str]]] = {
        q: set(s) for q, s in acquires.items()}
    for q in g.funcs:
        trans.setdefault(q, set())
    changed = True
    iters = 0
    while changed and iters < 20:
        changed = False
        iters += 1
        for qual, fi in g.funcs.items():
            cur = trans[qual]
            for callee in fi.calls:
                extra = trans.get(callee)
                if extra and not extra <= cur:
                    cur |= extra
                    changed = True

    edges: dict[tuple[str, str], set[tuple[str, str]]] = {}
    edge_sites: dict[tuple, tuple[str, int]] = {}

    for path, sf in idx.code.items():
        if sf.tree is None:
            continue
        locks = lock_env.get(path, {})
        if not locks:
            continue
        for fi in g._by_path.get(path, ()):
            for wnode, lock, body in _with_lock_items(fi.node, locks):
                held = locks[lock]
                for n in _shallow_walk(body):
                    # a) await under a threading lock
                    if isinstance(n, ast.Await):
                        out.append(Finding(
                            "GL07", path, n.lineno,
                            f"await while holding threading lock "
                            f"{lock!r} — the lock outlives the "
                            f"scheduling slice and can deadlock the "
                            f"loop against its own second acquirer; "
                            f"use asyncio.Lock or release before "
                            f"suspending"))
                    # b) known-lazy call under a lock
                    if isinstance(n, ast.Call):
                        name = dotted(n.func)
                        for lazy, why in tables.KNOWN_LAZY.items():
                            if name == lazy or \
                                    name.endswith("." + lazy):
                                site = f"{path}::{fi.scope}::{lazy}"
                                if site in tables.LAZY_UNDER_LOCK_OK:
                                    seen_declared.add(site)
                                    continue
                                out.append(Finding(
                                    "GL07", path, n.lineno,
                                    f"known-lazy callable {lazy!r} "
                                    f"({why}) called while holding "
                                    f"{lock!r} — first call "
                                    f"traces/compiles for seconds "
                                    f"under the lock; declare the "
                                    f"site in tables."
                                    f"LAZY_UNDER_LOCK_OK if the "
                                    f"serialization is deliberate"))
                    # c) acquisition edges: nested withs + same-file
                    # calls that acquire
                    if isinstance(n, (ast.With, ast.AsyncWith)):
                        for item in n.items:
                            d = dotted(item.context_expr)
                            if d in locks and locks[d] != held:
                                edges.setdefault(held, set()).add(
                                    locks[d])
                                edge_sites[(held, locks[d])] = \
                                    (path, n.lineno)
                    if isinstance(n, ast.Call):
                        t = None
                        # resolve the call through the context graph
                        # (match on the callee's SCOPE tail)
                        want = call_name(n.func)
                        for callee in fi.calls:
                            cfi = g.funcs.get(callee)
                            if cfi is not None and want and \
                                    cfi.scope.split(".")[-1] == want:
                                t = callee
                                break
                        if t is not None:
                            for other in trans.get(t, ()):
                                if other != held:
                                    edges.setdefault(
                                        held, set()).add(other)
                                    edge_sites[(held, other)] = \
                                        (path, n.lineno)

    # stale LAZY_UNDER_LOCK_OK entries: the declared site must still
    # exist AND still hold the lock across the lazy call — the
    # declaration IS the lock-extent contract (PR 8), not an excuse.
    # Full-tree runs only: on a narrowed scan the lock's DEFINING file
    # (mesh_codec for ring_codec's cross-file acquisition) may be
    # outside the scanned set, and an unresolvable lock must not read
    # as a dropped one.
    for site, reason in (tables.LAZY_UNDER_LOCK_OK.items()
                         if getattr(idx, "full_tree", True) else ()):
        path = site.split("::")[0]
        if path in idx.code and site not in seen_declared:
            out.append(Finding(
                "GL07", path, 1,
                f"stale tables.LAZY_UNDER_LOCK_OK entry {site!r} — "
                f"the site no longer holds a lock across that lazy "
                f"call (or is gone); delete the entry, or restore "
                f"the deliberate serialization it declared "
                f"(reason was: {reason})"))

    # cycle detection over the acquisition graph
    seen_cycles = set()
    for start in edges:
        stack = [(start, [start])]
        while stack:
            node, trail = stack.pop()
            for nxt in edges.get(node, ()):
                if nxt == start and len(trail) > 1:
                    cyc = frozenset(trail)
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    path, line = edge_sites.get(
                        (node, nxt), (start[0], 1))
                    pretty = " -> ".join(
                        lk for _, lk in trail + [start])
                    out.append(Finding(
                        "GL07", path, line,
                        f"lock-order cycle {pretty} — two threads "
                        f"interleaving these acquisitions deadlock; "
                        f"impose a single acquisition order"))
                elif nxt not in trail:
                    stack.append((nxt, trail + [nxt]))
    return out
