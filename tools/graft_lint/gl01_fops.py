"""GL01 — fop-vocabulary completeness.

Historical bugs this pins: PR 10's review pass had to fence ``xorv`` in
worm/bit-rot-stub/locks AFTER the fact (a new write fop slipped past
three brick-side gates), the xorv double-apply hazard (XOR is an
involution — blind idempotent retry self-cancels), and io-threads
classifying xorv NORMAL only because a reviewer noticed the slow queue
would invert it against its own wave's writevs.

Sub-checks, all driven by tables.py:

1. every ``Fop`` member is classified: WRITE_FOPS (core/fops.py) or
   tables.READ_CLASS, disjointly;
2. every write-class fop appears in changelog's E/D/M record classes
   or tables.CHANGELOG_EXEMPT;
3. every write-class fop has an explicit io-threads priority class
   (FAST/NORMAL/LEAST/UNGATED) or tables.IOT_SLOW_EXEMPT;
4. fence parity: each fence layer's gate set covers WRITE_FOPS up to
   its exemption table (and exemptions must not be stale);
5. ``_IDEMPOTENT_FOPS`` ⊆ read-class, and every string in it (and in
   ``_LOCK_FOPS``) names a real fop.
"""

from __future__ import annotations

import ast

from . import tables
from .astutil import class_def, dotted, eval_fop_set, \
    module_fop_sets, SetEvalError
from .engine import Finding, RepoIndex

FOPS_PATH = "glusterfs_tpu/core/fops.py"
CHANGELOG_PATH = "glusterfs_tpu/features/changelog.py"
IOT_PATH = "glusterfs_tpu/performance/io_threads.py"
CLIENT_PATH = "glusterfs_tpu/protocol/client.py"


def _vocabulary(tree: ast.Module) -> tuple[frozenset, int]:
    """(fop values, enum lineno) from the Fop enum class."""
    cls = class_def(tree, "Fop")
    vals = set()
    line = 1
    if cls is not None:
        line = cls.lineno
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Constant) and \
                    isinstance(stmt.value.value, str):
                vals.add(stmt.value.value)
    return frozenset(vals), line


def _named_set(tree: ast.Module, name: str,
               env: dict | None = None) -> tuple[frozenset, int] | None:
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == name:
            try:
                return eval_fop_set(stmt.value, env or {}), stmt.lineno
            except SetEvalError:
                return None
    return None


def check(idx: RepoIndex) -> list[Finding]:
    out: list[Finding] = []
    fops_sf = idx.code.get(FOPS_PATH)
    if fops_sf is None or fops_sf.tree is None:
        return out  # partial runs (explicit paths) skip cross-file checks
    vocab, vocab_line = _vocabulary(fops_sf.tree)
    got = _named_set(fops_sf.tree, "WRITE_FOPS")
    if not vocab or got is None:
        out.append(Finding("GL01", FOPS_PATH, vocab_line,
                           "could not extract Fop vocabulary or "
                           "WRITE_FOPS — the classification plane is "
                           "unchecked"))
        return out
    write_fops, wf_line = got

    # 1. read/write partition ---------------------------------------------
    unknown_write = write_fops - vocab
    for f in sorted(unknown_write):
        out.append(Finding("GL01", FOPS_PATH, wf_line,
                           f"WRITE_FOPS names {f!r} which is not in the "
                           "Fop vocabulary"))
    unclassified = vocab - write_fops - tables.READ_CLASS
    for f in sorted(unclassified):
        out.append(Finding(
            "GL01", FOPS_PATH, vocab_line,
            f"fop {f!r} is neither write-class (WRITE_FOPS) nor "
            "read-class (tools/graft_lint/tables.py READ_CLASS) — "
            "classify it explicitly"))
    for f in sorted(write_fops & tables.READ_CLASS):
        out.append(Finding(
            "GL01", FOPS_PATH, wf_line,
            f"fop {f!r} is BOTH in WRITE_FOPS and tables.READ_CLASS"))
    for f in sorted(tables.READ_CLASS - vocab):
        out.append(Finding(
            "GL01", FOPS_PATH, vocab_line,
            f"tables.READ_CLASS names {f!r} which is not in the Fop "
            "vocabulary (stale table)"))

    # 2. changelog E/D/M coverage -----------------------------------------
    ch = idx.code.get(CHANGELOG_PATH)
    if ch is not None and ch.tree is not None:
        sets = {}
        line = 1
        for nm in ("E_FOPS", "D_FOPS", "M_FOPS"):
            got = _named_set(ch.tree, nm)
            if got is not None:
                sets[nm], line = got
        journaled = frozenset().union(*sets.values()) if sets else \
            frozenset()
        for f in sorted(write_fops - journaled
                        - set(tables.CHANGELOG_EXEMPT)):
            out.append(Finding(
                "GL01", CHANGELOG_PATH, line,
                f"write-class fop {f!r} is in no changelog record "
                "class (E/D/M) — geo-rep would never see its "
                "mutations; journal it or exempt it in "
                "tables.CHANGELOG_EXEMPT with a reason"))
        for f, why in sorted(tables.CHANGELOG_EXEMPT.items()):
            if f in journaled:
                out.append(Finding(
                    "GL01", CHANGELOG_PATH, line,
                    f"stale exemption: {f!r} is journaled now — drop "
                    f"it from tables.CHANGELOG_EXEMPT ({why[:40]}...)"))

    # 3. io-threads priority classes --------------------------------------
    iot = idx.code.get(IOT_PATH)
    if iot is not None and iot.tree is not None:
        env = module_fop_sets(iot.tree)
        classed = frozenset().union(
            *(env.get(n, frozenset())
              for n in ("FAST", "NORMAL", "LEAST", "UNGATED")))
        line = next((s.lineno for s in iot.tree.body
                     if isinstance(s, ast.Assign)
                     and isinstance(s.targets[0], ast.Name)
                     and s.targets[0].id == "NORMAL"), 1)
        for f in sorted(write_fops - classed
                        - set(tables.IOT_SLOW_EXEMPT)):
            out.append(Finding(
                "GL01", IOT_PATH, line,
                f"write-class fop {f!r} has no explicit io-threads "
                "priority class — it falls to the slow queue, "
                "inverting it against sibling write fops of the same "
                "workload (the xorv-vs-writev wave hazard); classify "
                "it or exempt it in tables.IOT_SLOW_EXEMPT"))
        for f in sorted(set(tables.IOT_SLOW_EXEMPT) & classed):
            out.append(Finding(
                "GL01", IOT_PATH, line,
                f"stale exemption: {f!r} is classified now — drop it "
                "from tables.IOT_SLOW_EXEMPT"))

    # 4. fence parity ------------------------------------------------------
    for path, spec in tables.FENCES.items():
        sf = idx.code.get(path)
        if sf is None or sf.tree is None:
            continue  # partial runs skip absent fence layers
        gated, line = _gated_set(sf.tree, spec,
                                 {"WRITE_FOPS": write_fops,
                                  "Fop": vocab})
        exempt = spec["exempt"]
        for f in sorted(write_fops - gated - set(exempt)):
            out.append(Finding(
                "GL01", path, line,
                f"fence gap: write-class fop {f!r} is not gated by "
                f"{spec['layer']} while its siblings are — a new "
                "write fop must be fenced everywhere or exempted in "
                "tables.FENCES with a reason (the PR-10 xorv "
                "after-the-fact fence class)"))
        for f in sorted(set(exempt) & gated):
            out.append(Finding(
                "GL01", path, line,
                f"stale fence exemption: {spec['layer']} gates {f!r} "
                "now — drop it from tables.FENCES"))
        for f in sorted(set(exempt) - write_fops):
            out.append(Finding(
                "GL01", path, line,
                f"fence exemption {f!r} is not a write-class fop "
                "(stale table)"))

    # 5. idempotent-retry allowlist ---------------------------------------
    cl = idx.code.get(CLIENT_PATH)
    if cl is not None and cl.tree is not None:
        for name, must_be_read in (("_IDEMPOTENT_FOPS", True),
                                   ("_LOCK_FOPS", False)):
            found = _class_str_tuple(cl.tree, name)
            if found is None:
                continue
            vals, line = found
            for v in sorted(set(vals) - vocab):
                out.append(Finding(
                    "GL01", CLIENT_PATH, line,
                    f"{name} names {v!r} which is not a fop value "
                    "(typo pins nothing)"))
            if must_be_read:
                for v in sorted(set(vals) & write_fops):
                    out.append(Finding(
                        "GL01", CLIENT_PATH, line,
                        f"{name} contains write-class fop {v!r} — "
                        "blind re-dispatch of a write after a "
                        "transport failure double-applies it (the "
                        "xorv involution hazard, pinned forever)"))
    return out


def _gated_set(tree: ast.Module, spec: dict,
               env: dict[str, frozenset]) -> tuple[frozenset, int]:
    """The write-fop set a fence layer gates, per its declared kind."""
    if spec["kind"] == "loop":
        # module-level: for _f in <set-expr>: setattr(Class, _f.value,…)
        full_env = module_fop_sets(tree, seed=env)
        for stmt in tree.body:
            if not isinstance(stmt, ast.For):
                continue
            has_setattr = any(
                isinstance(c.func, ast.Name) and c.func.id == "setattr"
                for n in ast.walk(stmt)
                for c in ([n] if isinstance(n, ast.Call) else []))
            if not has_setattr:
                continue
            try:
                return (eval_fop_set(stmt.iter, full_env) &
                        env["WRITE_FOPS"], stmt.lineno)
            except SetEvalError:
                continue
        return frozenset(), 1
    # methods: write-fop-named async defs whose body calls a marker
    # or raises FopError before winding
    cls = class_def(tree, spec["layer"])
    if cls is None:
        return frozenset(), 1
    markers = set(spec.get("markers", ()))
    gated = set()
    for stmt in cls.body:
        if not isinstance(stmt, (ast.AsyncFunctionDef, ast.FunctionDef)):
            continue
        if stmt.name not in env["WRITE_FOPS"]:
            continue
        fences = False
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d.split(".")[-1] in markers:
                    fences = True
            if isinstance(n, ast.Raise) and isinstance(n.exc, ast.Call) \
                    and dotted(n.exc.func).endswith("FopError"):
                fences = True
        if fences:
            gated.add(stmt.name)
    return frozenset(gated), cls.lineno


def _class_str_tuple(tree: ast.Module,
                     attr: str) -> tuple[list, int] | None:
    """A class-level (or module-level) tuple/frozenset of string
    literals named ``attr``."""
    bodies = [tree.body]
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef):
            bodies.append(stmt.body)
    for body in bodies:
        for stmt in body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == attr:
                vals = [n.value for n in ast.walk(stmt.value)
                        if isinstance(n, ast.Constant)
                        and isinstance(n.value, str)]
                return vals, stmt.lineno
    return None
