"""GL09 — cross-context shared-state ownership (graft-race).

Historical bug: PR 12's ``maybe_initialize`` probe raced the
join-thread spawn window — a state attribute written by loop code and
read by a probe thread, with the transition invisible to review
because nothing DECLARED the attribute as cross-context.

The contract: an instance attribute written in one execution context
and touched in the other (per :mod:`ctxgraph`) is cross-context shared
state and must be accounted for, in order of preference:

1. **machine-verified lock-protected** — every cross-context access
   sits lexically inside a ``with <threading lock>:`` of the same
   class/module; nothing to declare, the code proves itself;
2. **immutable-after-start** — written only by context-UNKNOWN code
   (``__init__`` and other pre-concurrency setup); reads from either
   context are then safe by construction, nothing to declare;
3. **declared** — an entry in :data:`tables.OWNERSHIP` keyed
   ``path::Class.attr`` with a classification (``lock-protected`` for
   designs the lexical check cannot see, ``immutable-after-start``
   for hand-off-once fields, ``threadsafe-handoff`` for queues/
   events/GIL-atomic flags) and the reason.  New cross-context state
   is thereby a reviewed DATA edit, the graft-lint precedent.

Stale OWNERSHIP entries (attr no longer cross-context, or gone) are
findings too.
"""

from __future__ import annotations

import ast

from . import ctxgraph, tables
from .astutil import dotted
from .engine import Finding, RepoIndex

_CLASSIFICATIONS = ("lock-protected", "immutable-after-start",
                    "threadsafe-handoff")


def _lock_spans(fi: ctxgraph.FuncInfo, locks) -> list[tuple]:
    from .gl07_locks import _with_lock_items
    spans = []
    for wnode, _lock, _body in _with_lock_items(fi.node, locks):
        spans.append((wnode.lineno,
                      getattr(wnode, "end_lineno", wnode.lineno)))
    return spans


def _self_accesses(fi: ctxgraph.FuncInfo):
    """(attr, is_write, lineno) for every ``self.X`` touch in this
    function's own body."""
    for n in fi.body_walk():
        if isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Name) and n.value.id == "self":
            yield (n.attr, isinstance(n.ctx, (ast.Store, ast.Del)),
                   n.lineno)
        elif isinstance(n, ast.AugAssign) and \
                isinstance(n.target, ast.Attribute) and \
                isinstance(n.target.value, ast.Name) and \
                n.target.value.id == "self":
            # AugAssign target is Store; the read side is implicit
            yield (n.target.attr, True, n.lineno)


def check(idx: RepoIndex) -> list[Finding]:
    from .gl07_locks import _lock_env
    g = ctxgraph.build(idx)
    lock_env = _lock_env(idx)
    out: list[Finding] = []

    # group methods (nested closures included — they carry the
    # enclosing class) by (path, class)
    by_class: dict[tuple[str, str], list[ctxgraph.FuncInfo]] = {}
    for fi in g.funcs.values():
        if fi.cls is not None and fi.path in idx.code:
            by_class.setdefault((fi.path, fi.cls), []).append(fi)

    live_keys: set[str] = set()
    for (path, cls), methods in sorted(by_class.items()):
        locks = lock_env.get(path, {})
        # attr -> per-context access records
        acc: dict[str, dict] = {}
        ctxs_present = set()
        for fi in methods:
            ctx = g.ctx(fi.qual)
            if not ctx:
                # context-unknown code (constructors, CLI paths):
                # writes here are "before concurrency" — the
                # immutable-after-start auto-pass falls out of simply
                # not counting them
                continue
            if fi.scope.split(".")[-1] in ("__init__", "__new__"):
                # constructor writes happen before the object is
                # published to any other context (even when the
                # constructor itself runs under a classified context)
                continue
            ctxs_present |= ctx
            spans = _lock_spans(fi, locks)
            for attr, is_write, line in _self_accesses(fi):
                a = acc.setdefault(attr, _blank())
                locked = any(lo <= line <= hi for lo, hi in spans)
                for c in ctx:
                    key = ("write" if is_write else "read", c)
                    a["sites"].setdefault(key, []).append(
                        (line, locked))

        if not ({"loop", "thread"} <= ctxs_present):
            continue  # not a hybrid class

        for attr, a in sorted(acc.items()):
            sites = a["sites"]
            loop_w = sites.get(("write", "loop"), [])
            thr_w = sites.get(("write", "thread"), [])
            loop_r = sites.get(("read", "loop"), [])
            thr_r = sites.get(("read", "thread"), [])
            cross = (loop_w and (thr_r or thr_w)) or \
                    (thr_w and (loop_r or loop_w))
            key = f"{path}::{cls}.{attr}"
            if not cross:
                continue
            live_keys.add(key)
            declared = tables.OWNERSHIP.get(key)
            if declared is not None:
                cl = declared[0] if isinstance(declared, tuple) \
                    else None
                if cl not in _CLASSIFICATIONS:
                    first = (loop_w + thr_w + loop_r + thr_r)[0][0]
                    out.append(Finding(
                        "GL09", path, first,
                        f"tables.OWNERSHIP[{key!r}] classification "
                        f"{cl!r} is not one of {_CLASSIFICATIONS}"))
                continue
            # machine-verified lock-protected?  Writes must be locked,
            # and so must reads in a context some OTHER context writes
            # from; a read beside its own context's writes needs no
            # lock against itself.
            relevant = list(loop_w) + list(thr_w)
            if thr_w:
                relevant += loop_r
            if loop_w:
                relevant += thr_r
            if relevant and all(locked for _, locked in relevant):
                continue
            all_sites = loop_w + thr_w + loop_r + thr_r
            first = min(ln for ln, _ in all_sites)
            wctx = "loop" if loop_w else "thread"
            octx = "thread" if wctx == "loop" else "loop"
            out.append(Finding(
                "GL09", path, first,
                f"{cls}.{attr} is written in {wctx} context and "
                f"touched from {octx} context without a lock the "
                f"checker can see — cross-context state must be "
                f"lock-protected (with the class lock at every "
                f"site), immutable-after-start, or declared in "
                f"tables.OWNERSHIP[{key!r}] with its classification "
                f"and reason"))

    # stale declarations (full-tree runs only: cross-context liveness
    # depends on callers/seeds that may sit outside a narrowed scan)
    for key, entry in (tables.OWNERSHIP.items()
                       if getattr(idx, "full_tree", True) else ()):
        path = key.split("::")[0]
        if path in idx.code and key not in live_keys:
            reason = entry[1] if isinstance(entry, tuple) and \
                len(entry) > 1 else ""
            out.append(Finding(
                "GL09", path, 1,
                f"stale tables.OWNERSHIP entry {key!r} — the "
                f"attribute is no longer cross-context (or the class "
                f"is gone); delete the entry (reason was: {reason})"))
    return out


def _blank() -> dict:
    return {"sites": {}}
