"""GL05 — metrics-family discipline.

Historical bugs: the PR-8 review caught mesh families whose identical
label sets from two codec instances would collide in the exposition
(a ``{codec}`` label had to be added), and several PRs hand-verified
that family names asserted in tests/ci actually exist in the source.

Sub-checks:

1. every ``gftpu_*`` family is REGISTERED exactly once (a second
   registration call silently replaces the first — last-import-wins);
   registration is a registry call (``register`` /
   ``register_objects`` / ``counter`` / ``gauge``) or a synthesized
   snapshot entry (``merged["gftpu_x"] = {"type": ...}`` — the gateway
   supervisor's aggregation shape);
2. label-key consistency: the literal label dicts inside one
   registration's collector must share one key set (mixed key sets in
   one family break Prometheus scrapers);
3. every ``gftpu_*`` reference outside a registration — tests, docs,
   tools, code — names a registered family or a family-group prefix
   (``gftpu_rebalance_*``), so an assertion can never pin a family
   that does not exist.  ``ContextVar("gftpu_...")`` names are not
   families and are auto-exempt.
"""

from __future__ import annotations

import ast
import re

from . import tables
from .astutil import const_str, dotted, str_keys
from .engine import Finding, RepoIndex

_REG_METHODS = {"register", "register_objects", "counter", "gauge"}
_FAMILY_RE = re.compile(r"gftpu_[a-z0-9_]*[a-z0-9]")


def _registrations(sf) -> list[tuple[str, int, ast.AST]]:
    """(family, line, node) for registry calls AND synthesized
    snapshot-dict assignments."""
    out = []
    for n in ast.walk(sf.tree):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in _REG_METHODS and n.args:
            name = const_str(n.args[0])
            if name is not None and name.startswith("gftpu_"):
                out.append((name, n.lineno, n))
        elif isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Subscript) and \
                isinstance(n.value, ast.Dict):
            key = const_str(n.targets[0].slice)
            vkeys = str_keys(n.value)
            if key is not None and key.startswith("gftpu_") and \
                    vkeys is not None and "type" in vkeys:
                out.append((key, n.lineno, n))
    return out


def _nonfamily_strings(tree: ast.Module) -> set[int]:
    """ids of string nodes that name ContextVars, not families."""
    out = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and \
                dotted(n.func).split(".")[-1] == "ContextVar" and n.args:
            out.add(id(n.args[0]))
    return out


def check(idx: RepoIndex) -> list[Finding]:
    out: list[Finding] = []
    registered: dict[str, list[tuple[str, int]]] = {}
    reg_strings: set[int] = set()  # ids of registration name nodes

    # 1. registration census (tests count for resolution, never for
    # the duplicate check: test-local fixture families may repeat) ----
    def census(sf, report: bool):
        for name, line, node in _registrations(sf):
            if report:
                registered.setdefault(name, []).append((sf.path, line))
            else:
                registered.setdefault(name, [])
            if isinstance(node, ast.Call):
                reg_strings.add(id(node.args[0]))
            # 2. label-key consistency inside this registration
            key_sets = {}
            for n in ast.walk(node):
                if isinstance(n, ast.Dict) and n is not getattr(
                        node, "value", None):
                    keys = str_keys(n)
                    if keys is not None and keys and \
                            "type" not in keys:
                        key_sets.setdefault(frozenset(keys), n.lineno)
            if report and len(key_sets) > 1:
                shapes = " vs ".join(
                    "{" + ",".join(sorted(ks)) + "}"
                    for ks in sorted(key_sets, key=sorted))
                out.append(Finding(
                    "GL05", sf.path, line,
                    f"family {name!r} emits samples with mixed label "
                    f"key sets ({shapes}) — one family, one label "
                    "schema (the mesh codec-label collision class)"))

    for sf in idx.code.values():
        if sf.tree is not None:
            census(sf, report=True)
    for sf in idx.tests.values():
        if sf.tree is not None:
            census(sf, report=False)
    for name, sites in sorted(registered.items()):
        if len(sites) > 1:
            locs = ", ".join(f"{p}:{ln}" for p, ln in sites[1:])
            out.append(Finding(
                "GL05", sites[0][0], sites[0][1],
                f"family {name!r} is registered {len(sites)} times "
                f"(also at {locs}) — registration is last-wins, the "
                "earlier collector silently disappears"))

    fams = set(registered)

    # 3. references resolve ------------------------------------------------
    def resolve(token: str) -> bool:
        if token in fams or token in tables.NON_FAMILY_LITERALS:
            return True
        # family-group prefix at an underscore boundary
        # (docstrings say "the gftpu_rebalance_* families")
        return any(f.startswith(token + "_") for f in fams)

    for sf in idx.all_py().values():
        if sf.tree is None or sf.path.startswith("tools/graft_lint/") \
                or sf.path == "tests/test_graft_lint.py":
            continue  # the linter and its fixture corpus name fake
            # families on purpose
        ctxvars = _nonfamily_strings(sf.tree)
        for n in ast.walk(sf.tree):
            s = const_str(n) if isinstance(n, ast.Constant) else None
            if s is None or id(n) in reg_strings or id(n) in ctxvars:
                continue
            for token in _FAMILY_RE.findall(s):
                if not resolve(token):
                    out.append(Finding(
                        "GL05", sf.path, n.lineno,
                        f"reference to unregistered metrics family "
                        f"{token!r} — the assertion (or exposition "
                        "read) can never match a live registry"))
    for path, text in idx.docs.items():
        for i, line in enumerate(text.splitlines(), start=1):
            for token in _FAMILY_RE.findall(line):
                if not resolve(token):
                    out.append(Finding(
                        "GL05", path, i,
                        f"doc references unregistered metrics family "
                        f"{token!r}"))
    return out
