"""GL08 — task/future lifecycle (graft-race).

The two exact shapes behind real bugs on this tree:

* **PR 12**: ``loop.create_task(gw._serve_conn(...))`` whose result was
  dropped — the event loop holds only a WEAK reference to tasks, so
  the GC collected a live passed-fd serve task mid-connection and its
  ``__del__`` reset the socket.  Every ``create_task`` /
  ``ensure_future`` result must be RETAINED: assigned and then used
  (stored, awaited, callback-registered), passed along, returned, or
  awaited in place.
* **PR 7**: an event-pool job's future was orphaned on shutdown — a
  created future that is not resolved on EVERY path (exception edges
  included) wedges whoever awaits it.  For futures born via
  ``create_future()`` and never handed off, each path to function exit
  must ``set_result`` / ``set_exception`` / ``cancel``; a
  ``set_result`` inside a ``try`` whose handler neither resolves nor
  re-raises is the canonical miss.

Both checks are flow-sensitive within one function and deliberately
stop at escape: a future/task stored into a container or attribute,
passed to a call, or returned has transferred ownership — lifecycle
then belongs to the holder (and to GL09's ownership table if the
holder is cross-context shared state).
"""

from __future__ import annotations

import ast

from . import ctxgraph
from .astutil import call_name
from .engine import Finding, RepoIndex

_SPAWN = {"create_task", "ensure_future"}
_RESOLVE = {"set_result", "set_exception", "cancel"}
#: neutral observers: using the future this way neither resolves nor
#: hands it off
_OBSERVE = {"done", "cancelled", "result", "exception"}


def _parents(fn_node: ast.AST) -> dict[int, ast.AST]:
    out: dict[int, ast.AST] = {}
    stack = [fn_node]
    while stack:
        n = stack.pop()
        for c in ast.iter_child_nodes(n):
            out[id(c)] = n
            if not isinstance(c, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                stack.append(c)
    return out


# -- (a) task retention ----------------------------------------------------


def _task_findings(fi: ctxgraph.FuncInfo) -> list[Finding]:
    out = []
    spawn_calls = [n for n in fi.body_walk()
                   if isinstance(n, ast.Call)
                   and call_name(n.func) in _SPAWN]
    if not spawn_calls:
        return out
    parents = _parents(fi.node)
    for call in spawn_calls:
        p = parents.get(id(call))
        if isinstance(p, ast.Expr):
            out.append(Finding(
                "GL08", fi.path, call.lineno,
                "create_task/ensure_future result discarded — the "
                "loop holds only a weak reference; an un-retained "
                "task can be GC'd mid-flight (the PR-12 passed-fd "
                "serve-task bug).  Keep it: add to a set with an "
                "add_done_callback(discard), assign it, or await it"))
            continue
        if isinstance(p, (ast.Assign, ast.AnnAssign)) or \
                isinstance(p, ast.NamedExpr):
            targets = p.targets if isinstance(p, ast.Assign) \
                else [p.target]
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names:
                continue  # stored to attribute/subscript: retained
            used = False
            for n in fi.body_walk():
                if isinstance(n, ast.Name) and n.id in names and \
                        isinstance(n.ctx, ast.Load):
                    used = True
                    break
            if not used:
                out.append(Finding(
                    "GL08", fi.path, call.lineno,
                    f"task assigned to {names[0]!r} but never used — "
                    f"a local that dies at function exit does not "
                    f"retain the task (weak-ref GC hazard); store "
                    f"it, await it, or register a done callback"))
    return out


# -- (b) future resolution on all paths ------------------------------------


def _future_names(fi: ctxgraph.FuncInfo) -> list[tuple[str, ast.AST]]:
    out = []
    for n in fi.body_walk():
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name) and \
                isinstance(n.value, ast.Call) and \
                call_name(n.value.func) == "create_future":
            out.append((n.targets[0].id, n))
    return out


def _is_resolve(node: ast.AST, name: str) -> bool:
    """Does this subtree resolve ``name`` (set_result/exception/cancel
    directly on it)?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in _RESOLVE and \
                isinstance(n.func.value, ast.Name) and \
                n.func.value.id == name:
            return True
    return False


def _escapes(fi: ctxgraph.FuncInfo, name: str,
             parents: dict[int, ast.AST]) -> bool:
    """Any use of ``name`` that hands the future to someone else: call
    argument, return, yield, stored into an attribute/subscript/
    container, aliased, awaited after storing...  Conservative: any
    Load that is not a direct .set_*/.cancel/observer attribute access
    counts as an escape."""
    for n in fi.body_walk():
        if not (isinstance(n, ast.Name) and n.id == name
                and isinstance(n.ctx, ast.Load)):
            continue
        # direct attribute access on the name?
        parent = parents.get(id(n))
        if isinstance(parent, ast.Attribute) and \
                parent.attr in (_RESOLVE | _OBSERVE |
                                {"add_done_callback"}):
            continue
        if isinstance(parent, ast.Await):
            continue  # awaiting does not transfer ownership
        return True
    return False


class _Flow:
    """Tiny path-sensitive walk over the statement tree.  State per
    path is ``(ok, created)`` where ``ok`` means "no outstanding
    unresolved future on this path" (vacuously true before creation);
    creation flips ok False, a resolve flips it True.  Creation is
    detected uniformly during recursion, so a ``create_future()``
    nested in an if/try/with body is analyzed like a top-level one.
    ``raise`` ends a path harmlessly (an escaping exception means no
    caller ever saw the future); loops are approximated as
    zero-or-once for leak detection."""

    def __init__(self, name: str):
        self.name = name
        self.leak: int | None = None

    def block(self, stmts: list[ast.AST], ok: bool,
              created: bool) -> tuple[bool, bool, bool]:
        """Returns (ok_at_fallthrough, created_at_fallthrough,
        falls_through)."""
        for stmt in stmts:
            if self._creates(stmt):
                created, ok = True, False
                continue
            if isinstance(stmt, ast.Return):
                if not ok:
                    self.leak = self.leak or stmt.lineno
                return ok, created, False
            if isinstance(stmt, ast.Raise):
                return ok, created, False
            if isinstance(stmt, ast.If):
                o1, c1, f1 = self.block(stmt.body, ok, created)
                o2, c2, f2 = self.block(stmt.orelse, ok, created)
                if not f1 and not f2:
                    return ok, created or c1 or c2, False
                falls = ([(o1, c1)] if f1 else []) + \
                        ([(o2, c2)] if f2 else [])
                ok = all(o for o, _ in falls)
                created = any(c for _, c in falls)
                continue
            if isinstance(stmt, ast.Try):
                ob, cb, fb = self.block(stmt.body + stmt.orelse,
                                        ok, created)
                # exception edge: the raise may land between a
                # creation in the body and its resolve, so a handler
                # entered after an in-body creation starts not-ok
                body_creates = cb and not created
                ok_h = ok and not body_creates
                falls: list[tuple[bool, bool]] = []
                created_any = cb
                if fb:
                    falls.append((ob, cb))
                for h in stmt.handlers:
                    oh, ch, fh = self.block(h.body, ok_h,
                                            created or cb)
                    created_any = created_any or ch
                    if fh:
                        falls.append((oh, ch))
                if stmt.finalbody:
                    if self._resolves_list(stmt.finalbody):
                        falls = [(True, c) for _, c in falls] or \
                            [(True, created_any)]
                    _, _, ff = self.block(
                        stmt.finalbody,
                        bool(falls) and all(o for o, _ in falls),
                        created_any)
                    if not ff:
                        return (bool(falls) and
                                all(o for o, _ in falls),
                                created_any, False)
                if not falls:
                    return ok, created_any, False
                ok = all(o for o, _ in falls)
                created = created_any
                continue
            if isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                self.block(stmt.body, ok, created)
                self.block(stmt.orelse, ok, created)
                continue  # may run zero times: state unchanged
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                ok, created, ft = self.block(stmt.body, ok, created)
                if not ft:
                    return ok, created, False
                continue
            if self._resolves(stmt):
                ok = True
        return ok, created, True

    def _creates(self, stmt: ast.AST) -> bool:
        return isinstance(stmt, ast.Assign) and \
            len(stmt.targets) == 1 and \
            isinstance(stmt.targets[0], ast.Name) and \
            stmt.targets[0].id == self.name and \
            isinstance(stmt.value, ast.Call) and \
            call_name(stmt.value.func) == "create_future"

    def _resolves(self, stmt: ast.AST) -> bool:
        return _is_resolve(stmt, self.name)

    def _resolves_list(self, stmts: list[ast.AST]) -> bool:
        return any(_is_resolve(s, self.name) for s in stmts)


def _future_findings(fi: ctxgraph.FuncInfo) -> list[Finding]:
    out = []
    names = _future_names(fi)
    if not names:
        return out
    parents = _parents(fi.node)
    for name, creation in names:
        if _escapes(fi, name, parents):
            continue  # ownership transferred; holder's problem
        flow = _Flow(name)
        ok, created, falls = flow.block(
            list(getattr(fi.node, "body", [])), True, False)
        if falls and created and not ok:
            flow.leak = flow.leak or creation.lineno
        if flow.leak:
            out.append(Finding(
                "GL08", fi.path, flow.leak,
                f"future {name!r} can reach function exit unresolved "
                f"— whoever awaits it wedges forever (the PR-7 "
                f"orphaned event-pool future); resolve it on every "
                f"path, exception edges included (set_exception in "
                f"the handler or cancel in a finally)"))
    return out


def check(idx: RepoIndex) -> list[Finding]:
    g = ctxgraph.build(idx)
    out: list[Finding] = []
    for qual, fi in g.funcs.items():
        if fi.path not in idx.code or fi.scope == "<module>":
            continue
        if isinstance(fi.node, ast.Lambda):
            continue
        out.extend(_task_findings(fi))
        out.extend(_future_findings(fi))
    return out
