"""GL02 — option-plane consistency.

Historical bugs: volume-option keys drifting between read sites,
volgen registration and docs/volume_options.md (several review passes
caught one-end-only keys by hand), and SETVOLUME capability keys whose
client check site was forgotten (the sg/deadline/xorv family grew one
advertisement per PR).

Sub-checks:

1. every dotted option-shaped ``.get("x.y")`` read in code resolves to
   a key volgen registers (OPTION_MAP), or is exempted in
   tables.OPTION_READ_EXEMPT;
2. OPTION_MIN_OPVERSION ⊆ OPTION_MAP (an op-version for a key nobody
   maps gates nothing);
3. docs/volume_options.md == volgen.options_doc() regenerated
   (the one sub-check that imports repo code: the doc IS that
   function's output);
4. every SETVOLUME reply capability has a client check site
   (``res.get("<cap>")`` in protocol/client.py) or a tables.CAPABILITIES
   exemption, and the table itself carries no stale entries.
"""

from __future__ import annotations

import ast
import re

from . import tables
from .astutil import const_str, dotted
from .engine import Finding, RepoIndex

VOLGEN_PATH = "glusterfs_tpu/mgmt/volgen.py"
SERVER_PATH = "glusterfs_tpu/protocol/server.py"
CLIENT_PATH = "glusterfs_tpu/protocol/client.py"
DOC_PATH = "docs/volume_options.md"

_OPTION_KEY_RE = re.compile(
    r"^(?:%s)\.[a-z][a-z0-9.-]*$" % "|".join(tables.OPTION_KEY_PREFIXES))


def _volgen_tables(tree: ast.Module) -> tuple[dict, dict]:
    """(OPTION_MAP key->lineno, OPTION_MIN_OPVERSION key->lineno),
    following the literal assignment + ``.update({k: v for k in
    _Vn_KEYS})`` idiom."""
    opt_map: dict[str, int] = {}
    min_ver: dict[str, int] = {}
    tuples: dict[str, list[tuple[str, int]]] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            name = stmt.targets[0].id
            if isinstance(stmt.value, ast.Dict):
                keys = [(const_str(k), k.lineno if k else stmt.lineno)
                        for k in stmt.value.keys]
                if name == "OPTION_MAP":
                    opt_map.update({k: ln for k, ln in keys
                                    if k is not None})
                elif name == "OPTION_MIN_OPVERSION":
                    min_ver.update({k: ln for k, ln in keys
                                    if k is not None})
            elif isinstance(stmt.value, (ast.Tuple, ast.List)):
                tuples[name] = [(e.value, e.lineno)
                                for e in stmt.value.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)]
        # OPTION_MIN_OPVERSION.update({k: N for k in _Vn_KEYS})
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Call) and \
                dotted(stmt.value.func) == "OPTION_MIN_OPVERSION.update":
            arg = stmt.value.args[0] if stmt.value.args else None
            if isinstance(arg, ast.DictComp) and \
                    isinstance(arg.generators[0].iter, ast.Name):
                src = arg.generators[0].iter.id
                for k, ln in tuples.get(src, ()):
                    min_ver[k] = ln
            elif isinstance(arg, ast.Dict):
                for k in arg.keys:
                    s = const_str(k)
                    if s is not None:
                        min_ver[s] = k.lineno
    return opt_map, min_ver


def check(idx: RepoIndex) -> list[Finding]:
    out: list[Finding] = []
    vg = idx.code.get(VOLGEN_PATH)
    if vg is None or vg.tree is None:
        return out  # partial runs skip the cross-file option plane
    opt_map, min_ver = _volgen_tables(vg.tree)
    if not opt_map:
        out.append(Finding("GL02", VOLGEN_PATH, 1,
                           "could not extract OPTION_MAP — the option "
                           "plane is unchecked"))
        return out

    # 2. min-opversion keys must be mapped --------------------------------
    for k, ln in sorted(min_ver.items()):
        if k not in opt_map:
            out.append(Finding(
                "GL02", VOLGEN_PATH, ln,
                f"OPTION_MIN_OPVERSION entry {k!r} is not in "
                "OPTION_MAP — an op-version gate for an unmapped key "
                "gates nothing"))

    # 1. dotted option reads ----------------------------------------------
    valid = set(opt_map) | set(tables.OPTION_READ_EXEMPT)
    used_exempt: set[str] = set()
    for sf in idx.code.values():
        if sf.tree is None or sf.path.startswith("tools/graft_lint/"):
            continue  # the linter's own tables/docstrings name keys
        for n in ast.walk(sf.tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "get" and n.args):
                continue
            key = const_str(n.args[0])
            if key is None or not _OPTION_KEY_RE.match(key):
                continue
            if key in tables.OPTION_READ_EXEMPT:
                used_exempt.add(key)
                continue
            if key not in valid:
                out.append(Finding(
                    "GL02", sf.path, n.lineno,
                    f"option key {key!r} is read here but volgen's "
                    "OPTION_MAP does not register it — `volume set` "
                    "can never reach this site (key drift); map it or "
                    "exempt it in tables.OPTION_READ_EXEMPT"))
    for k in sorted(set(tables.OPTION_READ_EXEMPT) - used_exempt):
        out.append(Finding(
            "GL02", VOLGEN_PATH, 1,
            f"stale tables.OPTION_READ_EXEMPT entry {k!r}: no code "
            "reads it any more"))

    # 3. docs regenerate-and-diff -----------------------------------------
    committed = idx.docs.get(DOC_PATH)
    if committed is not None:
        try:
            from glusterfs_tpu.mgmt import volgen as _volgen
            want = _volgen.options_doc()
        except Exception as e:  # noqa: BLE001 - import env may lack jax
            out.append(Finding("GL02", DOC_PATH, 1,
                               f"could not regenerate options doc: {e!r}"))
        else:
            if committed != want:
                line = _first_diff_line(committed, want)
                out.append(Finding(
                    "GL02", DOC_PATH, line,
                    "docs/volume_options.md drifted from "
                    "volgen.options_doc() — regenerate: python -c "
                    "\"from glusterfs_tpu.mgmt.volgen import "
                    "options_doc; open('docs/volume_options.md','w')"
                    ".write(options_doc())\""))

    # 4. SETVOLUME capabilities -------------------------------------------
    out.extend(_check_capabilities(idx))
    return out


def _first_diff_line(a: str, b: str) -> int:
    for i, (la, lb) in enumerate(zip(a.splitlines(), b.splitlines()),
                                 start=1):
        if la != lb:
            return i
    return min(len(a.splitlines()), len(b.splitlines())) + 1


def _check_capabilities(idx: RepoIndex) -> list[Finding]:
    out: list[Finding] = []
    sv = idx.code.get(SERVER_PATH)
    cl = idx.code.get(CLIENT_PATH)
    if sv is None or sv.tree is None or cl is None or cl.tree is None:
        return out
    advertised: dict[str, int] = {}
    # the SETVOLUME reply: the dict literal carrying both "volume" and
    # "ok" keys
    for n in ast.walk(sv.tree):
        if isinstance(n, ast.Dict):
            keys = {const_str(k) for k in n.keys if k is not None}
            if {"volume", "ok"} <= keys:
                for k in n.keys:
                    s = const_str(k)
                    if s and s not in ("volume", "ok", "error"):
                        advertised[s] = k.lineno
    checked: set[str] = set()
    for n in ast.walk(cl.tree):
        if isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr == "get" and n.args:
            s = const_str(n.args[0])
            if s is not None:
                checked.add(s)
    for cap, ln in sorted(advertised.items()):
        spec = tables.CAPABILITIES.get(cap)
        if spec is None:
            out.append(Finding(
                "GL02", SERVER_PATH, ln,
                f"SETVOLUME advertises capability {cap!r} but "
                "tables.CAPABILITIES does not declare it — say where "
                "the client checks it (or why it never must)"))
        elif spec == "checked" and cap not in checked:
            out.append(Finding(
                "GL02", CLIENT_PATH, 1,
                f"capability {cap!r} is advertised at SETVOLUME but "
                "protocol/client.py never reads it from the handshake "
                "reply — the feature it gates can never arm"))
    for cap in sorted(set(tables.CAPABILITIES) - set(advertised)):
        out.append(Finding(
            "GL02", SERVER_PATH, 1,
            f"stale tables.CAPABILITIES entry {cap!r}: the SETVOLUME "
            "reply no longer advertises it"))
    return out
