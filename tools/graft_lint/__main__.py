"""``python -m tools.graft_lint [--json] [--changed] [paths]`` — the
no-path-games entry point (run.py stays the script-path form ci.sh and
lint.sh call; both share main())."""

import sys

from .run import main

if __name__ == "__main__":
    sys.exit(main())
