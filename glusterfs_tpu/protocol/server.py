"""protocol/server — serves a brick graph over TCP, with auth and TLS.

Reference: xlators/protocol/server (actor table server-rpc-fops_v2.c:6132,
per-client fd tables + resolver, auth).  Here: an asyncio TCP service in
front of a layer graph.  Per-connection state mirrors ``client_t``: an fd
table (wire FdHandle -> live FdObj), the client's lk-owner prefix, and
disconnect cleanup that drops fds and lock grants (the reference's lock
reaping on disconnect).

Protocol: framed records (rpc/wire.py); a CALL carries
``[fop_name, args, kwargs]``; fd arguments travel as FdHandle; replies
carry the fop return (or MT_ERROR + FopError).  The handshake
(SETVOLUME analog) is the first call: ``__handshake__`` with the client
identity, requested subvolume name, and credentials; no other fop is
dispatched before it succeeds.

Auth mirrors xlators/protocol/auth: ``auth-reject``/``auth-allow`` are
address pattern lists checked in that order (auth/addr), and
``auth-user``/``auth-password`` is the login scheme (auth/login) —
glusterd generates per-volume credentials that volgen writes into both
the brick and client volfiles, the reference's trusted-volfile model.
TLS is the socket.c SSL analog: ``ssl on`` plus cert/key/ca paths turns
the listener into a TLS endpoint (ssl stdlib), with optional mutual
auth when a CA is configured.

The ``protocol/server`` graph layer itself is a passthrough that only
carries these options (the reference's server xlator at the top of every
brick volfile); BrickServer reads them from the graph top.
"""

from __future__ import annotations

import asyncio
import errno
import fnmatch
import hmac
import os
import socket
import ssl as ssl_mod
import time
from typing import Any

from ..core.events import gf_event
from ..core.fops import Fop, FopError
from ..core.layer import FdObj, Layer, register
from ..core.options import Option
from ..core import gflog, tracing
from ..core import metrics as _metrics
from ..rpc import shm as _shm
from ..rpc import wire
from ..rpc.event_pool import TURN_MIN, EventPool

log = gflog.get_logger("protocol.server")


@register("protocol/server")
class ServerLayer(Layer):
    """Option-carrying top of a brick graph (server xlator analog).

    All fops pass through; BrickServer enforces the auth/TLS options
    (the reference's server_setvolume + rpc-transport/socket do the
    same outside the fop path, server.c auth via gf_authenticate)."""

    OPTIONS = (
        Option("auth-allow", "str", default="*",
               description="comma-separated address patterns allowed to "
                           "connect (auth.addr.<brick>.allow)"),
        Option("auth-reject", "str", default="",
               description="comma-separated address patterns refused "
                           "(auth.addr.<brick>.reject; wins over allow)"),
        Option("auth-user", "str", default="",
               description="login username (auth.login.<brick>.allow)"),
        Option("auth-password", "str", default="",
               description="login password (auth.login.<user>.password)"),
        Option("auth-mgmt-user", "str", default="",
               description="management credential pair: written only "
                           "into the brick volfile (never served to "
                           "clients) so glusterd's reconfigure/statedump "
                           "calls pass even when auth.allow excludes "
                           "this host"),
        Option("auth-mgmt-password", "str", default=""),
        Option("ssl", "bool", default="off",
               description="serve TLS on the brick port (socket.c SSL)"),
        Option("ssl-cert", "str", default="",
               description="PEM certificate path (ssl-cert-file)"),
        Option("ssl-key", "str", default="",
               description="PEM private-key path (ssl-private-key)"),
        Option("ssl-ca", "str", default="",
               description="PEM CA bundle; when set, client certificates "
                           "are required and verified (ssl-ca-list)"),
        Option("ssl-allow", "str", default="",
               description="comma-separated certificate CN patterns "
                           "allowed to SETVOLUME (auth.ssl-allow, "
                           "server.c:1857): per-identity TLS auth on "
                           "top of CA verification.  Empty = any "
                           "verified cert.  Requires ssl + ssl-ca "
                           "(without a verified peer cert every "
                           "handshake is refused)"),
        Option("event-threads", "int", default=2, min=0, max=64,
               description="frame-turning workers for this brick's "
                           "transport (server.event-threads; the "
                           "multithreaded-epoll analog, "
                           "event-epoll.c): decode, payload handling "
                           "and reply encode of large frames move "
                           "off the accept loop onto a keyed worker "
                           "pool — a connection's frames are turned "
                           "by one worker at a time (per-connection "
                           "ordering preserved) while distinct "
                           "connections turn in parallel.  0 = turn "
                           "inline on the event loop (the pre-9 "
                           "serial plane).  Live-reconfigurable: the "
                           "pool grows/shrinks without dropping "
                           "in-flight frames"),
        Option("compound-fops", "bool", default="on",
               description="serve compound fop chains and advertise "
                           "the capability at SETVOLUME "
                           "(cluster.use-compound-fops server half); "
                           "off = clients fall back to single fops"),
        Option("trace-fops", "bool", default="on",
               description="advertise trace-span re-arming at SETVOLUME "
                           "and adopt the client's trailing trace-id "
                           "frame field before dispatching into the "
                           "brick graph, so brick-side spans join the "
                           "client's trace "
                           "(diagnostics.trace-propagation server "
                           "half); off = the field is ignored and "
                           "clients stop sending it"),
        Option("sg-replies", "bool", default="on",
               description="serve scatter-gather reply payloads: a "
                           "readv (or chain-link) reply held as several "
                           "buffers rides the frame as a blob VECTOR "
                           "(one gathered send, no join copy) to "
                           "clients that advertised sg at SETVOLUME "
                           "(network.zero-copy-reads server half); "
                           "off = replies are joined to single blobs"),
        Option("shm-transport", "bool", default="on",
               description="advertise the same-host shared-memory bulk "
                           "lane at SETVOLUME (network.shm-transport "
                           "server half; the RDMA-transport analog, "
                           "rpc/shm): blob payloads to/from colocated "
                           "clients ride memfd arenas exchanged over "
                           "an AF_UNIX side-channel, descriptors ride "
                           "the socket.  Read per-frame: off "
                           "live-downgrades every reply to inline "
                           "blobs without a reconnect"),
        Option("shm-arena-size", "size", default="16MB", min=65536,
               description="per-direction shared-memory arena size for "
                           "the shm bulk lane (network.shm-arena-size). "
                           "A frame whose blobs don't fit the free ring "
                           "ships inline — sizing is throughput tuning, "
                           "never correctness"),
        Option("listen-backlog", "int", default=1024, min=0,
               description="accept-queue depth for the brick listener "
                           "(transport.listen-backlog; socket.c default "
                           "1024 — a connect storm at volume start must "
                           "not see ECONNREFUSED)"),
        Option("address-family", "enum", default="inet",
               values=("inet", "inet6"),
               description="listener address family "
                           "(transport.address-family)"),
        Option("allow-insecure", "bool", default="on",
               description="accept client connections from unprivileged "
                           "(>1023) source ports (server.allow-insecure; "
                           "rpcsvc auth model).  Off = classic secure-"
                           "port check"),
        Option("tcp-user-timeout", "time", default="0",
               description="TCP_USER_TIMEOUT on accepted connections "
                           "(server.tcp-user-timeout)"),
        Option("keepalive-time", "time", default="20",
               description="TCP_KEEPIDLE (server.keepalive-time)"),
        Option("keepalive-interval", "time", default="2",
               description="TCP_KEEPINTVL (server.keepalive-interval)"),
        Option("keepalive-count", "int", default=9, min=0,
               description="TCP_KEEPCNT (server.keepalive-count)"),
        Option("tcp-window-size", "size", default="0",
               description="SO_RCVBUF/SO_SNDBUF on accepted "
                           "connections (network.tcp-window-size)"),
        Option("outstanding-rpc-limit", "int", default=64, min=0,
               max=65536,
               description="per-client cap on in-flight requests: at the "
                           "limit the brick stops reading that client's "
                           "connection until replies drain, so one "
                           "misbehaving or merely fast client cannot "
                           "balloon brick memory or starve others "
                           "(rpcsvc_request_outstanding, rpcsvc.c:211-250; "
                           "default rpcsvc.h:38).  0 = unlimited.  Lock "
                           "fops are exempt from the count — a limit full "
                           "of blocked locks would otherwise never admit "
                           "the unlock that frees them (rpcsvc.c:183-208)"),
        Option("qos", "bool", default="off",
               description="per-client QoS admission control "
                           "(features/qos): token-bucket rate limits "
                           "by client identity, enforced at frame "
                           "admission — overdrafts are refused with a "
                           "retryable EAGAIN carrying a qos-throttle "
                           "notice (retry-after) in the error xdata, "
                           "answered over the healthy transport so the "
                           "client circuit breaker never counts them"),
        Option("qos-fops-per-sec", "int", default=0, min=0,
               description="per-client fop admission rate; 0 = "
                           "unlimited.  Lock-class and lease/release "
                           "fops are exempt (shedding an unlock or a "
                           "recall ack would deadlock the very client "
                           "being shaped)"),
        Option("qos-bytes-per-sec", "size", default="0",
               description="per-client wire-byte rate (request frames "
                           "charged at admission, reply frames debited "
                           "after send — a greedy reader borrows "
                           "against its bucket and the debt delays its "
                           "next admission); 0 = unlimited"),
        Option("qos-burst", "time", default="1",
               description="bucket depth in seconds of the configured "
                           "rate: how much a quiet client may burst "
                           "before shaping starts"),
        Option("qos-shaped-window", "time", default="2",
               description="quiet time after the last shed/shape "
                           "before a client's THROTTLE_STOP fires "
                           "(lifecycle events are transition-edge "
                           "only)"),
        Option("qos-soft-quota-delay", "time", default="0.05",
               description="per-write-fop admission delay for clients "
                           "over their quota SOFT limit "
                           "(features/quota): shaped via TCP "
                           "backpressure, not errored — the hard "
                           "limit still returns EDQUOT"),
        Option("qos-rebalance-throttle", "enum", default="normal",
               values=("lazy", "normal", "aggressive"),
               description="fops/s pacing of the rebalance-origin "
                           "admission lane (lazy=64, normal=512, "
                           "aggressive=unpaced) — the cluster.rebal-"
                           "throttle table re-expressed as a QoS lane; "
                           "the lane paces (sleeps), never sheds: "
                           "migration fops are not idempotent"),
    )

    _TRANSPORT_OPTS = ("ssl", "ssl-cert", "ssl-key", "ssl-ca")

    def reconfigure(self, options: dict) -> None:
        """TLS material is bound to the live listener at start(): a
        cert/key/ca change cannot take effect in-place, so refuse the
        live path — glusterd then falls back to a respawn, which picks
        the new material up (cert rotation must not silently no-op)."""
        from ..core.options import validate_options

        new = validate_options(self.OPTIONS, options)
        if any(new[k] != self.opts[k] for k in self._TRANSPORT_OPTS):
            raise RuntimeError("TLS transport change needs a restart")
        super().reconfigure(options)


def _addr_match(addr: str, patterns: str) -> bool:
    return any(fnmatch.fnmatch(addr, p.strip())
               for p in patterns.split(",") if p.strip())


def _peer_cn(cert) -> str | None:
    """commonName from a parsed TLS peer certificate (ssl module's
    getpeercert() dict shape), or None when absent/unverified."""
    for rdn in (cert or {}).get("subject", ()):
        for key, value in rdn:
            if key == "commonName":
                return value
    return None


def _ct_eq(a, b) -> bool:
    """Constant-time credential comparison (timing side-channel)."""
    if not isinstance(a, str) or not isinstance(b, str):
        return False
    return hmac.compare_digest(a.encode("utf-8", "surrogateescape"),
                               b.encode("utf-8", "surrogateescape"))

_FOPS = {f.value for f in Fop}
# lock-class fops never count against outstanding-rpc-limit
# (rpcsvc_can_outstanding_req_be_ignored, rpcsvc.c:183-208): a limit
# full of blocked lock requests would stop the connection being read,
# and the unlock that would unblock them could then never arrive
_THROTTLE_EXEMPT = {"inodelk", "finodelk", "entrylk", "fentrylk", "lk"}
# non-wire-fop methods a client may invoke remotely (heal entry points,
# introspection — the reference exposes these via separate RPC programs)
_RPC_EXTRAS = {"heal_info", "heal_file", "heal_entry", "rebalance",
               "release", "getactivelk", "quota_usage", "top_stats",
               "metrics_dump", "changelog_history",
               "contend_held_locks", "clear_locks"}

#: the deep-status op family (GF_CLI_STATUS_* brick half) — the ONE
#: definition; glusterd's fan-out and the CLI parser import it
STATUS_KINDS = ("detail", "clients", "fds", "inodes", "callpool", "mem")

#: fops whose replies are worth encoding on the event pool: bulk data
#: (readv, compound chains with readv links) or structure-heavy tagged
#: bodies (listings, status/statedump dumps).  Everything else encodes
#: inline — a stat reply is ~200 bytes and the thread handoff would
#: cost more than the encode.
_BULKY_REPLY_FOPS = {"readv", "readdir", "readdirp", "getxattr",
                     "fgetxattr", "compound", "__compound__",
                     "__status__", "__statedump__"}


class _ClientConn:
    def __init__(self, server: "BrickServer", writer: asyncio.StreamWriter):
        self.server = server
        self.writer = writer
        self.fds: dict[int, FdObj] = {}
        self.next_fd = 1
        self.identity = b""
        self.name = ""
        self.authed = False
        self.is_mgmt = False
        self.peer_addr = "?"
        self.peercert = None  # parsed TLS peer cert (CN allow-listing)
        self.compress = False  # mirror zlib frames after handshake
        self.sg = False  # peer understands scatter-gather replies
        # the brick this transport bound to at SETVOLUME (multiplexed
        # processes serve several; glusterfsd-mgmt.c ATTACH model)
        self.top: Layer | None = None
        self.graph = None
        # -- per-client accounting (the client_t dump of server.c) ----
        # maintained inline in the frame read/write paths: integer
        # adds on buffers the transport already holds, zero extra
        # syscalls, no per-fop allocation
        self.connected_at = time.time()
        self.bytes_rx = 0
        self.bytes_tx = 0
        self.fop_counts: dict[str, int] = {}
        self.caps: dict = {}  # capabilities advertised at SETVOLUME
        self.opversion = 0    # peer build's op-version (0 = pre-8 peer)
        # traffic origin from the handshake creds ("rebalance" rides
        # the paced QoS lane; "" / "client" is ordinary traffic)
        self.origin = ""
        # outstanding-rpc occupancy (status callpool reads these; they
        # replace the old _serve-closure locals)
        self.inflight = 0
        self.exempt_inflight = 0
        # same-host shared-memory bulk lane (rpc/shm): armed per
        # direction by the SETVOLUME side-channel.  shm_tx stays None
        # until the client confirms its rx mapping (__shm_ok__) — an
        # FL_SHM reply must never race the peer's arming
        self.shm_rx = None
        self.shm_tx = None
        self.shm_tx_armed = False
        self.shm_token = ""

    def info(self) -> dict:
        """One ``volume status clients`` row (client_t dump shape)."""
        total = sum(self.fop_counts.values())
        return {"client": self.identity.hex(),
                "addr": self.peer_addr,
                "subvol": self.name,
                "connected_since": self.connected_at,
                "uptime": time.time() - self.connected_at,
                "op_version": self.opversion,
                "caps": sorted(self.caps),
                "bytes_rx": self.bytes_rx,
                "bytes_tx": self.bytes_tx,
                "fops": total,
                "fop_counts": dict(self.fop_counts),
                "opened_fds": len(self.fds),
                "inflight": self.inflight + self.exempt_inflight,
                "origin": self.origin,
                "shm": ("armed" if self.shm_tx_armed
                        else "rx" if self.shm_rx is not None else "off"),
                "mgmt": self.is_mgmt}

    def register_fd(self, fd: FdObj) -> wire.FdHandle:
        fdid = self.next_fd
        self.next_fd += 1
        self.fds[fdid] = fd
        return wire.FdHandle(fdid, fd.gfid, fd.path)

    def resolve(self, v: Any) -> Any:
        if isinstance(v, wire.FdHandle):
            fd = self.fds.get(v.fdid)
            if fd is None:
                raise FopError(errno.EBADFD, f"stale fd {v.fdid}")
            return fd
        if isinstance(v, dict):
            if "__anon_fd__" in v:  # anonymous fd addressed by gfid
                return FdObj(v["__anon_fd__"], path=v.get("path", ""),
                             anonymous=True)
            return {k: self.resolve(x) for k, x in v.items()}
        if isinstance(v, list):
            return [self.resolve(x) for x in v]
        return v

    # reply payloads at or above this ride the out-of-band blob lane
    # (readv data must not crawl through the tagged codec byte-wise)
    BLOB_MIN = 4096

    def wrap(self, v: Any) -> Any:
        if isinstance(v, FdObj):
            return self.register_fd(v)
        if isinstance(v, wire.SGBuf):
            # scatter-gather reply (readv served from several buffers):
            # each segment becomes its own trailing blob — writelines
            # gathers them into one send with no join copy.  A peer
            # that didn't advertise sg (or a disabled brick) gets the
            # joined single buffer it expects.
            if self.sg and len(v.segments) > 1:
                return {wire.SG_KEY: [
                    wire.Blob(s) if len(s) >= self.BLOB_MIN else bytes(s)
                    for s in v.segments]}
            one = v.segments[0] if len(v.segments) == 1 else v.tobytes()
            return wire.Blob(one) if len(one) >= self.BLOB_MIN \
                else bytes(one)
        if isinstance(v, (bytes, bytearray, memoryview)) and \
                len(v) >= self.BLOB_MIN:
            return wire.Blob(v)
        if isinstance(v, tuple):
            return [self.wrap(x) for x in v]
        if isinstance(v, list):
            return [self.wrap(x) for x in v]
        if isinstance(v, dict):
            return {k: self.wrap(x) for k, x in v.items()}
        return v


# live brick servers, scraped by the unified registry (weakref: a
# stopped server's families age out with the GC).  Per-client series
# are labeled by brick + client-uid prefix so the Prometheus endpoint
# answers "who is connected and what are they consuming" per brick.
_LIVE_SERVERS = _metrics.REGISTRY.register_objects(
    "gftpu_server_clients", "gauge",
    "authenticated client connections per served brick",
    lambda s: s._client_gauge_samples())
_metrics.REGISTRY.register_objects(
    "gftpu_server_client_bytes_total", "counter",
    "wire bytes exchanged per authenticated client connection",
    lambda s: list(s._client_byte_samples()), live=_LIVE_SERVERS)
_metrics.REGISTRY.register_objects(
    "gftpu_server_client_fops_total", "counter",
    "fops dispatched per authenticated client connection",
    lambda s: s._client_fop_samples(), live=_LIVE_SERVERS)


class BrickServer:
    """TCP service for one brick graph top (the brick process core)."""

    def __init__(self, top: Layer, host: str = "127.0.0.1", port: int = 0,
                 graph=None):
        self.top = top
        self.host = host
        self.port = port
        self.graph = graph  # enables live option reconfigure
        # multiplexing (glusterfsd-mgmt.c ATTACH): additional brick
        # graphs served on this same transport, keyed by served top name
        self.attached: dict[str, tuple[Layer, Any]] = {}
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[_ClientConn] = set()
        # the concurrent event plane (server.event-threads): keyed
        # frame-turning workers shared by every connection (and every
        # multiplexed brick) on this transport
        self._pool: EventPool | None = None
        # QoS admission engines (features/qos), one per served top —
        # created lazily on the first option-carrying connection so
        # bare-Layer test servers never pay for the plane
        self._qos: dict[str, Any] = {}
        # shm side-channel (rpc/shm): abstract AF_UNIX listener that
        # hands arena memfds to token-bearing clients via SCM_RIGHTS;
        # tokens are one-shot and bind the dial to a SETVOLUME'd
        # transport
        self._shm_srv: asyncio.AbstractServer | None = None
        self._shm_addr = ""
        self._shm_tokens: dict[str, _ClientConn] = {}
        _LIVE_SERVERS.add(self)

    # -- QoS admission (features/qos; server.qos-* options) ----------------

    def _qos_of(self, top: Layer):
        """The admission engine for a served top; None when the top
        carries no options (bare-Layer test servers).  Option values
        are read per-verdict inside the engine, so ``volume set``
        retunes live buckets."""
        opts = self._opts_of(top)
        if not opts or "qos" not in opts:
            return None
        eng = self._qos.get(top.name)
        if eng is None:
            from ..features.qos import QosEngine

            eng = self._qos[top.name] = QosEngine(
                top.name, lambda: self._opts_of(top),
                soft_fn=lambda: self._soft_quota_clients(top))
        return eng

    @staticmethod
    def _soft_quota_clients(top: Layer):
        """Identities currently over a quota SOFT limit, pulled from
        any quota layers in the served graph (features/quota exposes
        qos_soft_clients) — the backpressure half of the QoS plane."""
        from ..core.layer import walk

        out: set = set()
        for layer in walk(top):
            fn = getattr(layer, "qos_soft_clients", None)
            if fn is not None:
                try:
                    out |= set(fn())
                except Exception:  # noqa: BLE001 - probe must not shed
                    pass
        return out

    def _lane_of(self, conn: _ClientConn) -> str:
        """io-threads lane of the request being dispatched (rides
        wire.CURRENT_LANE): least-priority for rebalance-origin and
        currently-shaped clients when QoS is on."""
        top = conn.top if conn.top is not None else self.top
        eng = self._qos.get(top.name)
        if eng is None or conn.is_mgmt:
            return ""
        return eng.lane(conn.identity, conn.origin)

    # -- per-client metrics families (scraped by core/metrics.REGISTRY) ----

    def _served_name(self, conn: _ClientConn) -> str:
        return (conn.top if conn.top is not None else self.top).name

    def _authed_conns(self, top: Layer | None = None) -> list[_ClientConn]:
        return [c for c in self.connections
                if c.authed and (top is None or
                                 (c.top if c.top is not None
                                  else self.top) is top)]

    def _metric_conns(self) -> list[_ClientConn]:
        """Real clients only: every mgmt poll shares the identity
        b"glusterd", so two concurrent fan-outs would emit duplicate
        label sets — an invalid Prometheus exposition (status rows
        still list mgmt conns, flagged)."""
        return [c for c in self._authed_conns() if not c.is_mgmt]

    def _client_gauge_samples(self):
        per_brick: dict[str, int] = {}
        for c in self._metric_conns():
            per_brick[self._served_name(c)] = \
                per_brick.get(self._served_name(c), 0) + 1
        return [({"brick": b}, n) for b, n in per_brick.items()]

    def _client_byte_samples(self):
        for c in self._metric_conns():
            labels = {"brick": self._served_name(c),
                      "client": c.identity.hex()[:8]}
            yield {**labels, "dir": "rx"}, c.bytes_rx
            yield {**labels, "dir": "tx"}, c.bytes_tx

    def _client_fop_samples(self):
        return [({"brick": self._served_name(c),
                  "client": c.identity.hex()[:8]},
                 sum(c.fop_counts.values()))
                for c in self._metric_conns()]

    def _shm_advert(self, conn: _ClientConn, creds: dict,
                    top: Layer):
        """SETVOLUME shm advert (rpc/shm): only to peers that asked,
        never under frame compression (inlined frames carry no blobs),
        and only when the lane can actually arm here — option on,
        side-channel listening, platform support.  The returned token
        is one-shot and pairs the side-channel dial with THIS
        transport."""
        if not creds.get("shm-transport") or creds.get("compress"):
            return None
        if not self._shm_on(top) or self._shm_srv is None \
                or not _shm.supported():
            return None
        token = os.urandom(16).hex()
        conn.shm_token = token
        self._shm_tokens[token] = conn
        return {"boot-id": _shm.boot_id(), "addr": self._shm_addr,
                "token": token}

    def _select_top(self, name: str) -> tuple[Layer, Any]:
        """SETVOLUME routing: the requested remote-subvolume picks the
        brick graph (default brick when unnamed or named directly).
        Clients name the brick ('v-brick-0'); attached graphs are keyed
        by their served top ('v-brick-0-server') — accept either.

        A nonempty name matching neither the default graph nor any
        attached graph fails the handshake explicitly (the reference's
        server_setvolume "remote-subvolume not found" error) instead of
        silently authing the client against the wrong graph — on a mux
        daemon that produced an opaque 'authentication failed' from the
        anchor's auth-reject, masking the real condition."""
        if not name:
            return self.top, self.graph
        for key in (name, name + "-server"):
            if key in self.attached:
                return self.attached[key]
        if name == self.top.name or name + "-server" == self.top.name:
            return self.top, self.graph
        if self.graph is None or \
                name in getattr(self.graph, "by_name", {}):
            # bare-Layer servers (no graph) cannot enumerate their
            # subvolumes; graph-backed ones accept any layer by name
            # (the reference resolves remote-subvolume anywhere in the
            # brick volfile)
            return self.top, self.graph
        raise FopError(errno.ENOENT,
                       f"unknown remote-subvolume {name!r}")

    @staticmethod
    def _opts_of(top: Layer):
        """Live options of a protocol/server top layer, if present
        (read per-use so ``volume set`` reconfigure takes effect)."""
        return top.opts if isinstance(top, ServerLayer) else {}

    @property
    def _auth_opts(self):
        return self._opts_of(self.top)

    def _ssl_context(self) -> ssl_mod.SSLContext | None:
        # one TLS identity per transport: multiplexed bricks share the
        # anchor brick's certificate (the reference's mux shares the
        # rpcsvc listener the same way)
        opts = self._auth_opts
        if not opts or not opts["ssl"]:
            return None
        from ..rpc import tls

        return tls.server_context(opts["ssl-cert"], opts["ssl-key"],
                                  opts["ssl-ca"])

    def _addr_ok(self, addr: str, top: Layer | None = None) -> bool:
        """auth/addr: reject list wins, then the allow list must match."""
        opts = self._opts_of(top if top is not None else self.top)
        if not opts:
            return True
        if opts["auth-reject"] and _addr_match(addr, opts["auth-reject"]):
            return False
        return _addr_match(addr, opts["auth-allow"])

    def _is_mgmt(self, creds: dict, top: Layer | None = None) -> bool:
        """The volfile-only mgmt pair: glusterd's own calls pass even
        when the address lists exclude this host."""
        opts = self._opts_of(top if top is not None else self.top)
        return bool(opts and opts["auth-mgmt-user"]
                    and _ct_eq(creds.get("username"),
                               opts["auth-mgmt-user"])
                    and _ct_eq(creds.get("password"),
                               opts["auth-mgmt-password"]))

    def _ssl_cn_ok(self, conn: "_ClientConn",
                   top: Layer | None = None) -> bool:
        """auth.ssl-allow: when the brick carries a CN allow-list, the
        peer must have presented a VERIFIED certificate whose CN
        matches one pattern (reference server.c:1857 ssl_allow — a
        valid cert with the wrong identity is still refused)."""
        opts = self._opts_of(top if top is not None else self.top)
        allow = opts.get("ssl-allow", "") if opts else ""
        if not allow:
            return True
        cn = _peer_cn(conn.peercert)
        return cn is not None and _addr_match(cn, allow)

    def _compound_on(self, top: Layer | None = None) -> bool:
        """Serve/advertise compound chains?  Read per-use so a live
        volume-set of cluster.use-compound-fops applies immediately."""
        opts = self._opts_of(top if top is not None else self.top)
        if not opts:
            return True  # bare graphs (tests): capability always on
        return bool(opts.get("compound-fops", True))

    def _sg_on(self, top: Layer | None = None) -> bool:
        """Serve scatter-gather replies?  Read per-use so a live
        volume-set of network.zero-copy-reads applies immediately."""
        opts = self._opts_of(top if top is not None else self.top)
        if not opts:
            return True  # bare graphs (tests): capability always on
        return bool(opts.get("sg-replies", True))

    def _shm_on(self, top: Layer | None = None) -> bool:
        """Serve the shared-memory bulk lane?  Read per-frame so a
        live volume-set of network.shm-transport downgrades every
        reply to inline blobs immediately, no reconnect."""
        opts = self._opts_of(top if top is not None else self.top)
        if not opts:
            return True  # bare graphs (tests): capability always on
        return bool(opts.get("shm-transport", True))

    def _shm_arena_size(self, top: Layer | None = None) -> int:
        opts = self._opts_of(top if top is not None else self.top)
        try:
            return int(opts.get("shm-arena-size", _shm.DEFAULT_ARENA))
        except (TypeError, ValueError):
            return _shm.DEFAULT_ARENA

    def _trace_on(self, top: Layer | None = None) -> bool:
        """Re-arm client trace ids?  Read per-use so a live volume-set
        of diagnostics.trace-propagation applies immediately."""
        opts = self._opts_of(top if top is not None else self.top)
        if not opts:
            return True  # bare graphs (tests): capability always on
        return bool(opts.get("trace-fops", True))

    def _login_ok(self, creds: dict, top: Layer | None = None) -> bool:
        """auth/login: when the brick carries credentials, the client
        must present the matching pair (server_setvolume
        gf_authenticate)."""
        opts = self._opts_of(top if top is not None else self.top)
        if not opts or not opts["auth-user"]:
            return True
        return (_ct_eq(creds.get("username"), opts["auth-user"])
                and _ct_eq(creds.get("password"), opts["auth-password"]))

    def _wire_upcall(self, top: Layer) -> None:
        from ..core.layer import walk

        for layer in walk(top):
            sink = getattr(layer, "set_upcall_sink", None)
            if sink is not None:
                sink(self.push_event)

    async def attach(self, volfile_text: str,
                     top_name: str | None = None) -> str:
        """Serve another brick graph on this transport (the brick-mux
        ATTACH RPC, glusterfsd-mgmt.c:913)."""
        from ..core.graph import Graph

        graph = Graph.construct(volfile_text, top_name=top_name)
        name = graph.top.name
        if name == self.top.name or name in self.attached:
            raise FopError(errno.EEXIST, f"brick {name!r} already served")
        try:
            await graph.activate()
        except BaseException:
            # activate inits bottom-up: layers below the failing one
            # are live (fds, background tasks) — fini them or every
            # retried attach leaks another set
            try:
                await graph.fini()
            except Exception:
                pass
            raise
        self._wire_upcall(graph.top)
        self.attached[name] = (graph.top, graph)
        log.info(8, "attached brick %s (now %d on this port)", name,
                 1 + len(self.attached))
        return name

    async def detach(self, name: str) -> bool:
        """Stop serving an attached brick; its bound transports drop
        (glusterfsd-mgmt.c brick terminate for mux bricks)."""
        entry = self.attached.pop(name, None)
        if entry is None:
            return False
        top, graph = entry
        for conn in list(self.connections):
            if conn.top is top:
                try:
                    conn.writer.close()
                except Exception:
                    pass
                self.connections.discard(conn)
                await self._cleanup(conn)
        try:
            await graph.fini()
        except Exception as e:
            log.warning(9, "detach fini of %s: %r", name, e)
        return True

    async def start(self) -> int:
        opts = self._opts_of(self.top)
        backlog = int(opts.get("listen-backlog", 1024) or 1024)
        family = {"inet": socket.AF_INET,
                  "inet6": socket.AF_INET6}.get(
                      str(opts.get("address-family", "inet")),
                      socket.AF_UNSPEC)
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port, ssl=self._ssl_context(),
            backlog=backlog, family=family)
        self.port = self._server.sockets[0].getsockname()[1]
        # shm bulk-lane side-channel (rpc/shm): an abstract-namespace
        # AF_UNIX listener (no filesystem residue, dies with the
        # process) where same-host clients trade their SETVOLUME token
        # for the two arena memfds.  Failure to bind is not an error —
        # the lane simply never advertises and every peer stays inline
        if _shm.supported():
            try:
                name = f"\0gftpu-shm-{os.getpid()}-{id(self):x}"
                self._shm_srv = await asyncio.start_unix_server(
                    self._shm_serve, path=name)
                self._shm_addr = "@" + name[1:]
            except Exception as e:  # noqa: BLE001 - lane is optional
                log.warning(9, "shm side-channel unavailable: %r", e)
                self._shm_srv = None
                self._shm_addr = ""
        # hand the event-push callback to any upcall layer in the graph
        # (the reference's upcall xlator calls back through rpcsvc the
        # same way)
        self._wire_upcall(self.top)
        # spin the event plane up with the listener so the
        # gftpu_event_threads families are scrapable from volume start
        self.event_pool()
        log.info(1, "brick %s serving on %s:%d", self.top.name, self.host,
                 self.port)
        return self.port

    async def _shm_serve(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        """Side-channel dial: one-shot token -> two arena memfds via
        SCM_RIGHTS ([c2s, s2c]; the same-host proof is that the fds
        map at all).  The c2s rx arena is armed BEFORE the fds leave
        this process, so the client's first FL_SHM call frame always
        finds a reader; the s2c tx arena stays payload-disarmed until
        the client confirms its own mapping (__shm_ok__)."""
        fd_c2s = fd_s2c = -1
        try:
            line = await asyncio.wait_for(reader.readline(), 5.0)
            token = line.decode(errors="replace").strip()
            conn = self._shm_tokens.pop(token, None) if token else None
            if conn is None or conn.shm_rx is not None:
                return
            top = conn.top if conn.top is not None else self.top
            size = max(_shm.HDR_SIZE + 4096, self._shm_arena_size(top))
            rx, fd_c2s = _shm.ShmRx.create(size)
            conn.shm_rx = rx
            tx, fd_s2c = _shm.ShmTx.create(size)
            conn.shm_tx = tx
            # sendmsg on a dup'd raw socket: the asyncio TransportSocket
            # wrapper deprecates direct sendmsg, and the transport must
            # keep owning its fd
            sock = writer.get_extra_info("socket")
            raw = socket.socket(fileno=os.dup(sock.fileno()))
            try:
                socket.send_fds(raw, [b"ok"], [fd_c2s, fd_s2c])
            finally:
                raw.close()
            log.info(8, "shm lane mapped for client %s (%d bytes/dir)",
                     conn.identity.hex()[:8], size)
        except Exception as e:  # noqa: BLE001 - peer falls back inline
            log.warning(9, "shm fd exchange failed: %r", e)
        finally:
            for fd in (fd_c2s, fd_s2c):
                if fd >= 0:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
            try:
                writer.close()
            except Exception:
                pass

    def push_event(self, targets: list[bytes], payload: dict) -> None:
        """Send an MT_EVENT frame to each connected client in targets
        (xid 0: events correlate to no call)."""
        frame = wire.pack(0, wire.MT_EVENT, payload)
        for conn in list(self.connections):
            if conn.identity in targets:
                try:
                    conn.writer.write(frame)
                except Exception:
                    pass

    def _event_threads(self) -> int:
        """Configured pool width, read per-use so a live volume-set of
        server.event-threads applies without a respawn."""
        opts = self._auth_opts
        if not opts:
            return self.DEFAULT_EVENT_THREADS
        try:
            return int(opts.get("event-threads",
                                self.DEFAULT_EVENT_THREADS))
        except (TypeError, ValueError):
            return self.DEFAULT_EVENT_THREADS

    def event_pool(self) -> EventPool:
        """The transport's frame-turning pool, reconciled to the live
        option (one int compare on the hot path).  A stopped server's
        pool stays in place, shut down — its size-0 state turns any
        straggling reply inline instead of resurrecting worker threads
        nobody would ever stop again."""
        pool = self._pool
        if pool is None:
            pool = self._pool = EventPool(self._event_threads(),
                                          name=self.top.name)
        elif not pool.closed:
            pool.ensure(self._event_threads())
        return pool

    async def stop(self) -> None:
        if self._pool is not None:
            # shut down but keep the handle: an in-flight serve_one
            # reaching send() after stop() must not construct a fresh
            # pool (leaked threads); turn() on a closed pool is inline
            self._pool.shutdown()
        if self._shm_srv is not None:
            self._shm_srv.close()
            self._shm_srv = None
            self._shm_addr = ""
        self._shm_tokens.clear()
        if self._server is not None:
            self._server.close()
            # close live connections too: since py3.12 wait_closed() also
            # waits for connection handlers, which would block forever on
            # clients that keep their sockets open
            for conn in list(self.connections):
                try:
                    conn.writer.close()
                except Exception:
                    pass
            await self._server.wait_closed()
            self._server = None

    # -- connection handling ----------------------------------------------

    # an unauthenticated peer must complete SETVOLUME within this long,
    # or the transport is dropped (no fd squatting / pre-auth probing)
    HANDSHAKE_DEADLINE = 10.0
    # rpcsvc.h:38 RPCSVC_DEFAULT_OUTSTANDING_RPC_LIMIT (used when the
    # served top carries no protocol/server options, e.g. bare graphs)
    DEFAULT_RPC_LIMIT = 64
    # server.event-threads default (the reference ships 2 since 3.8;
    # used directly when the served top carries no protocol/server
    # options, e.g. bare graphs in tests)
    DEFAULT_EVENT_THREADS = 2
    # lock-class fops are exempt from the limit (deadlock hack,
    # rpcsvc.c:183-208) but a hostile flood of them must still not OOM
    # the brick: a wide separate cap bounds parked lock tasks.  The
    # reference leaves these genuinely unbounded; we keep the exemption
    # property for any sane workload and cap the pathological one
    EXEMPT_HARD_CAP = 16384

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        """Per-frame dispatch runs CONCURRENTLY (reference rpcsvc +
        io-threads): requests are read in order but each is served in
        its own task, with replies interleaving as they finish — the
        client correlates by xid.  A blocking fop (a queued lock) must
        not starve heartbeats behind it; serial dispatch also capped
        wire throughput at one fop round-trip at a time."""
        peer = writer.get_extra_info("peername") or ("?",)
        opts = self._opts_of(self.top)
        if not opts.get("allow-insecure", True) and len(peer) > 1 and \
                isinstance(peer[1], int) and peer[1] > 1023:
            # classic secure-port check (server.allow-insecure off):
            # only root-bound source ports may talk to the brick
            log.warning(7, "refusing unprivileged port %s:%s", *peer[:2])
            writer.close()
            return
        from ..rpc.socktune import tune_socket

        tune_socket(writer.get_extra_info("socket"),
                    keepalive_time=opts.get("keepalive-time", 20),
                    keepalive_interval=opts.get("keepalive-interval", 2),
                    keepalive_count=opts.get("keepalive-count", 9),
                    user_timeout=opts.get("tcp-user-timeout", 0),
                    window_size=opts.get("tcp-window-size", 0))
        conn = _ClientConn(self, writer)
        conn.peer_addr = str(peer[0])
        # TLS identity for auth.ssl-allow: only present when the
        # listener verified a client certificate (ssl + ssl-ca)
        conn.peercert = writer.get_extra_info("peercert")
        self.connections.add(conn)
        tasks: set[asyncio.Task] = set()
        wlock = asyncio.Lock()
        # inbound backpressure (server.outstanding-rpc-limit;
        # rpcsvc_request_outstanding rpcsvc.c:211-250): when this client
        # has `limit` unanswered requests, stop reading its connection —
        # TCP flow control then bounds its queue to the socket buffers.
        # The limit is read per-admission so reconfigure applies live.
        # Occupancy lives ON the conn so `volume status callpool` can
        # read each client's outstanding count.
        gate = asyncio.Event()
        gate.set()

        def _limit() -> int:
            top = conn.top if conn.top is not None else self.top
            try:
                return int(self._opts_of(top).get(
                    "outstanding-rpc-limit", self.DEFAULT_RPC_LIMIT))
            except (TypeError, ValueError):
                return self.DEFAULT_RPC_LIMIT

        async def send(xid: int, resp_type, resp,
                       bulky: bool = False) -> None:
            # reply encode: bulky replies turn on the event pool —
            # keyed by conn, so one connection's encodes stay mutually
            # exclusive while distinct connections encode in parallel;
            # small replies encode inline (the handoff would cost more
            # than the encode).  Encoding happens OUTSIDE the write
            # lock: only the socket write serializes.
            pool = self.event_pool()
            turn = bulky and pool.size > 0
            if conn.compress:
                if turn:
                    buf = await pool.turn(conn, wire.pack_z,
                                          xid, resp_type, resp)
                else:
                    buf = wire.pack_z(xid, resp_type, resp)
                frames = [buf]
            else:
                # blob replies (readv data) go out as raw trailing
                # buffers — no payload copy between the fop return
                # and the socket.  With the shm lane armed (and the
                # option still on — read per-frame so a live
                # volume-set downgrades instantly), blob bytes ride
                # the shared arena and only descriptors hit the wire
                lane = conn.shm_tx \
                    if (conn.shm_tx_armed and not conn.shm_tx.dead
                        and self._shm_on(conn.top if conn.top is not None
                                         else self.top)) else None
                if turn:
                    frames = await pool.turn(conn, wire.pack_frames,
                                             xid, resp_type, resp, lane)
                else:
                    frames = wire.pack_frames(xid, resp_type, resp, lane)
            nbytes = sum(len(f) for f in frames)
            if conn.authed and not conn.is_mgmt and self._qos:
                # reply-byte debit (features/qos): a greedy reader's
                # big readv replies borrow against its bytes bucket —
                # the debt delays its NEXT admission
                eng = self._qos.get((conn.top if conn.top is not None
                                     else self.top).name)
                if eng is not None:
                    eng.charge(conn.identity, nbytes)
            async with wlock:
                conn.bytes_tx += nbytes
                writer.writelines(frames)
                await writer.drain()

        async def serve_one(xid: int, payload, kind: str):
            fop = payload[0] if isinstance(payload, list) and payload \
                else None
            bulky = fop in _BULKY_REPLY_FOPS
            try:
                try:
                    resp_type, resp = await self._dispatch(conn, payload)
                    await send(xid, resp_type, resp, bulky)
                except (ConnectionError, RuntimeError):
                    pass
                except Exception as e:
                    # a reply wire.pack can't serialize must still
                    # ANSWER the xid — a silently dead task would hang
                    # the client's call forever while pings keep passing
                    log.error(2, "reply serialization failed: %r", e)
                    try:
                        await send(xid, wire.MT_ERROR,
                                   FopError(errno.EIO,
                                            f"unserializable reply: "
                                            f"{e!r}"))
                    except Exception:
                        pass
            finally:
                if kind == "throttled":
                    conn.inflight -= 1
                    gate.set()
                elif kind == "exempt":
                    conn.exempt_inflight -= 1
                    gate.set()

        try:
            while True:
                try:
                    if conn.authed:
                        rec = await wire.read_frame(reader)
                    else:
                        rec = await asyncio.wait_for(
                            wire.read_frame(reader),
                            self.HANDSHAKE_DEADLINE)
                except (asyncio.IncompleteReadError, ConnectionError,
                        asyncio.TimeoutError):
                    break
                # rx accounting: record + the 4-byte length prefix —
                # one integer add per frame already in hand
                conn.bytes_rx += len(rec) + 4
                # frame decode: large records turn on the event pool.
                # Awaiting the decode BEFORE the next read_frame is
                # what preserves per-connection dispatch order — the
                # pool's key serialization covers the encode side,
                # where several of this connection's replies can be
                # in flight at once.
                pool = self.event_pool()
                try:
                    if len(rec) >= TURN_MIN and pool.size > 0:
                        try:
                            xid, mtype, payload = await pool.turn(
                                conn, wire.unpack, rec, conn.shm_rx)
                        except (asyncio.CancelledError,
                                wire.ShmDecodeError):
                            raise
                        except Exception:
                            # undecodable frame: drop the transport
                            break
                    else:
                        xid, mtype, payload = wire.unpack(rec,
                                                          conn.shm_rx)
                except wire.ShmDecodeError as e:
                    # an FL_SHM frame this end can't serve (lane not
                    # armed / arena gone / malformed table): ANSWER it
                    # — EOPNOTSUPP + the shm-unsupported notice makes
                    # the peer disarm and resend inline, instead of
                    # its call hanging out the deadline.  Disarm OUR
                    # half too: the peer tears its arenas down on the
                    # notice, so any further FL_SHM reply from here
                    # would be undecodable over there
                    log.warning(9, "shm frame refused: %s", e)
                    conn.shm_tx_armed = False
                    try:
                        await send(wire.peek_xid(rec), wire.MT_ERROR,
                                   FopError(errno.EOPNOTSUPP, str(e),
                                            {"shm-unsupported": True}))
                    except ConnectionError:
                        break
                    continue
                if mtype != wire.MT_CALL:
                    continue
                if conn.authed and isinstance(payload, list) and payload \
                        and payload[0] == "__ping__":
                    # reserved heartbeat lane: pings bypass the
                    # outstanding-rpc gate, else a limit's worth of
                    # fops blocked on a held lock would starve the very
                    # liveness probe the concurrency exists to protect
                    try:
                        await send(xid, wire.MT_REPLY, "pong")
                    except ConnectionError:
                        break
                    continue
                if not conn.authed:
                    # SETVOLUME runs inline: everything else is gated
                    # on its outcome
                    resp_type, resp = await self._dispatch(conn, payload)
                    try:
                        buf = wire.pack(xid, resp_type, resp)
                        conn.bytes_tx += len(buf)
                        writer.write(buf)
                        await writer.drain()
                    except ConnectionError:
                        break
                    if not conn.authed:
                        break  # refused SETVOLUME: drop the transport
                    continue
                fop = payload[0] if isinstance(payload, list) and payload \
                    else None
                # QoS admission (features/qos, server.qos-*): the
                # verdict lands BEFORE the outstanding-rpc gate — a
                # shed frame must not occupy an admission slot.  Sheds
                # are ANSWERED (EAGAIN + retry-after in the error
                # xdata) over the healthy transport, so the client's
                # circuit breaker structurally cannot count them; and
                # the frame was never dispatched, so the client may
                # retry ANY fop.  Shapes (soft-quota pressure, the
                # rebalance lane) sleep the read loop instead — TCP
                # flow control slows the sender, nothing errors.
                if not conn.is_mgmt:
                    eng = self._qos_of(conn.top if conn.top is not None
                                       else self.top)
                    if eng is not None:
                        verdict, wait_s, why = eng.admit(
                            conn.identity, fop=str(fop or ""),
                            nbytes=len(rec) + 4, origin=conn.origin)
                        if verdict == "shed":
                            try:
                                await send(xid, wire.MT_ERROR, FopError(
                                    errno.EAGAIN, "qos throttled",
                                    {"qos-throttle": {
                                        "retry-after": round(wait_s, 4),
                                        "reason": why}}))
                            except ConnectionError:
                                break
                            continue
                        if verdict == "shape":
                            await asyncio.sleep(wait_s)
                limit = _limit()
                if limit <= 0:
                    kind = "free"  # operator chose unlimited
                elif fop in _THROTTLE_EXEMPT:
                    while conn.exempt_inflight >= self.EXEMPT_HARD_CAP:
                        gate.clear()
                        await gate.wait()
                    conn.exempt_inflight += 1
                    kind = "exempt"
                else:
                    # re-read the limit each pass, with a bounded wait:
                    # a live volume-set raising the limit must unpark an
                    # already-throttled connection even if none of its
                    # parked requests ever completes (nothing else would
                    # set the gate)
                    while 0 < _limit() <= conn.inflight:  # stop reading
                        gate.clear()
                        try:
                            await asyncio.wait_for(gate.wait(), 1.0)
                        except asyncio.TimeoutError:
                            pass
                    conn.inflight += 1
                    kind = "throttled"
                t = asyncio.create_task(serve_one(xid, payload, kind))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
        finally:
            for t in tasks:
                t.cancel()
            self.connections.discard(conn)
            await self._cleanup(conn)
            try:
                writer.close()
            except Exception:
                pass

    async def _cleanup(self, conn: _ClientConn) -> None:
        """Disconnect: release fds + this client's locks (client_t reap)."""
        top = conn.top if conn.top is not None else self.top
        if conn.authed and not conn.is_mgmt:
            # lifecycle event with the final account (events.h
            # EVENT_CLIENT_DISCONNECT); mgmt polls (glusterd status/
            # profile sweeps) are excluded on both edges — they would
            # drown the history in self-inflicted noise
            gf_event("CLIENT_DISCONNECT", client=conn.identity.hex(),
                     brick=top.name, server=self.top.name,
                     bytes_rx=conn.bytes_rx, bytes_tx=conn.bytes_tx,
                     fops=sum(conn.fop_counts.values()))
        # shm lane teardown: drop both arenas (rx close defers while
        # consumer views are alive — the last GC'd view completes it;
        # a dead CLIENT's mappings die with its process, so nothing
        # here can leak across a peer SIGKILL either way)
        if conn.shm_token:
            self._shm_tokens.pop(conn.shm_token, None)
            conn.shm_token = ""
        conn.shm_tx_armed = False
        for arena in (conn.shm_tx, conn.shm_rx):
            if arena is not None:
                try:
                    arena.close()
                except Exception:
                    pass
        conn.shm_tx = conn.shm_rx = None
        for fd in conn.fds.values():
            rel = getattr(top, "release", None)
            if rel is not None:
                try:
                    await rel(fd)
                except Exception:
                    pass
        conn.fds.clear()
        if conn.identity:
            from ..core.layer import walk

            for layer in walk(top):
                rc = getattr(layer, "release_client", None)
                if rc is not None:
                    try:
                        rc(conn.identity)
                    except Exception:
                        pass
            eng = self._qos.get(top.name)
            if eng is not None and not conn.is_mgmt:
                eng.release_client(conn.identity)

    # -- deep volume status (GF_CLI_STATUS_{DETAIL,CLIENTS,INODE,FD,
    # CALLPOOL,MEM} brick half, glusterd-op-sm.c op family) ---------------

    STATUS_KINDS = STATUS_KINDS

    def _status_of(self, top: Layer, kind: str) -> dict:
        """One brick's share of ``volume status <kind>`` — everything
        is read from live state already in memory; ``detail`` adds one
        statvfs (cold path)."""
        from ..core.layer import walk

        if kind == "clients":
            eng = self._qos.get(top.name)
            rows = []
            for c in self._authed_conns(top):
                row = c.info()
                if eng is not None and not c.is_mgmt:
                    # per-client shaping view (features/qos): whether
                    # this identity is inside a throttle window, its
                    # shed/shape counts, and the live bucket balances
                    row["qos"] = eng.client_view(c.identity)
                rows.append(row)
            return {"clients": rows}
        if kind == "fds":
            out = []
            for c in self._authed_conns(top):
                out.append({"client": c.identity.hex(),
                            "count": len(c.fds),
                            "fds": [{"fd": fdid, "path": fd.path,
                                     "gfid": fd.gfid.hex(),
                                     "flags": fd.flags}
                                    for fdid, fd in c.fds.items()]})
            return {"fd_tables": out,
                    "total": sum(e["count"] for e in out)}
        if kind == "inodes":
            tables = {}
            identity = {}
            for layer in walk(top):
                it = getattr(layer, "itable", None)
                if it is not None and hasattr(it, "dump"):
                    tables[layer.name] = it.dump()
                if hasattr(layer, "_ino_cache"):
                    # storage/posix: the brick-side identity caches are
                    # its inode table analog (gfid handle store)
                    identity[layer.name] = {
                        "ino_cache": len(layer._ino_cache),
                        "xattr_cache": len(layer._xa_cache),
                        "uncompacted_bindings": len(layer._gfid_mem),
                        "dirty": len(layer._xa_dirty)}
            return {"itables": tables, "identity": identity}
        if kind == "callpool":
            pools = []
            locks = []
            leases = []
            for layer in walk(top):
                q = getattr(layer, "queued", None)
                ex = getattr(layer, "executed", None)
                if isinstance(q, list) and isinstance(ex, list):
                    pools.append({"layer": layer.name,
                                  "queued": list(q),
                                  "executed": list(ex)})
                # the lock wedge view (ISSUE 9): per-domain blocked
                # counts + oldest-holder age, so an operator sees a
                # wedge before revocation fires
                ls = getattr(layer, "lock_status", None)
                if ls is not None:
                    locks.append({"layer": layer.name, **ls()})
                # the lease wedge view (ISSUE 16): held/recalling
                # counts + oldest-holder age beside the locks table
                les = getattr(layer, "lease_status", None)
                if les is not None:
                    leases.append({"layer": layer.name, **les()})
            return {"io_threads": pools,
                    "locks": locks,
                    "leases": leases,
                    "outstanding": [
                        {"client": c.identity.hex(),
                         "inflight": c.inflight,
                         "exempt": c.exempt_inflight}
                        for c in self._authed_conns(top)]}
        if kind == "mem":
            import resource

            return {"registry": _metrics.REGISTRY.snapshot(),
                    "max_rss_kb":
                        resource.getrusage(
                            resource.RUSAGE_SELF).ru_maxrss}
        if kind == "detail":
            import os as _os

            bricks = []
            for layer in walk(top):
                root = getattr(layer, "root", None)
                if not isinstance(root, str) or \
                        not hasattr(layer, "_failed_health"):
                    continue
                row = {"layer": layer.name, "path": root,
                       "health": ("failed" if layer._failed_health
                                  else "ok"),
                       "health_error": layer._failed_health,
                       "reserve_limited":
                           bool(getattr(layer, "_reserve_full", False))}
                try:
                    sv = _os.statvfs(root)
                    row.update(block_size=sv.f_bsize,
                               blocks_total=sv.f_blocks,
                               blocks_free=sv.f_bfree,
                               blocks_avail=sv.f_bavail,
                               inodes_total=sv.f_files,
                               inodes_free=sv.f_ffree)
                except OSError as e:
                    row["statvfs_error"] = str(e)
                bricks.append(row)
            return {"backends": bricks}
        raise FopError(errno.EINVAL,
                       f"unknown status kind {kind!r} "
                       f"(one of {', '.join(self.STATUS_KINDS)})")

    async def _dispatch(self, conn: _ClientConn, payload: Any):
        try:
            # a trailing 4th element is the client's trace id (only sent
            # when this brick advertised trace at SETVOLUME; a payload
            # from an older client is the bare 3-element triple)
            fop_name, args, kwargs = payload[0], payload[1], payload[2]
            trace_id = payload[3] if len(payload) > 3 else None
            # deadline budget (network.deadline-propagation): the
            # client's remaining call budget rides a reserved request
            # field, popped HERE so fop signatures never see it, and
            # armed as an absolute local-clock deadline for this
            # request's context — io-threads drops work the client has
            # already abandoned
            budget = None
            if isinstance(kwargs, dict):
                budget = kwargs.pop("__deadline__", None)
            if isinstance(budget, (int, float)) and budget > 0:
                wire.CURRENT_DEADLINE.set(
                    asyncio.get_running_loop().time() + float(budget))
            if fop_name == "__handshake__":
                creds = args[2] if len(args) > 2 else {}
                want = args[1] if len(args) > 1 else ""
                # routing first: auth is checked against the BRICK the
                # client asked for (each mux'd graph carries its own
                # volume's credentials)
                try:
                    top, graph = self._select_top(want)
                except FopError as e:
                    log.warning(7, "handshake from %s: %s",
                                conn.peer_addr, e)
                    return wire.MT_REPLY, {"ok": False, "error": str(e)}
                # mgmt pair (volfile-only, never served to clients)
                # bypasses BOTH address lists — an over-broad
                # auth.reject must not cut glusterd off from its bricks
                is_mgmt = self._is_mgmt(creds or {}, top)
                ok = is_mgmt or (
                    self._addr_ok(conn.peer_addr, top)
                    and self._login_ok(creds or {}, top)
                    and self._ssl_cn_ok(conn, top))
                if not ok:
                    log.warning(7, "handshake refused from %s (%r)",
                                conn.peer_addr, args[0])
                    return wire.MT_REPLY, {"ok": False,
                                           "error": "authentication failed"}
                conn.identity = args[0]
                conn.name = want
                conn.authed = True
                conn.is_mgmt = is_mgmt
                conn.top, conn.graph = top, graph
                conn.compress = bool((creds or {}).get("compress"))
                # traffic origin (rebalance daemons ride the paced QoS
                # lane; carried in creds so the FIRST post-handshake
                # frame is already attributed — and a reconnect's fresh
                # handshake re-carries it)
                conn.origin = str((creds or {}).get("origin") or "")
                # sg replies only flow to peers that asked for them
                # (mixed-version: an old client never sees an sg dict)
                conn.sg = bool((creds or {}).get("sg-replies")) and \
                    self._sg_on(top)
                # client accounting: remember what the peer advertised
                # (the client_t dump's "capabilities" column) and stamp
                # the connect time from NOW — the pre-auth probe window
                # is not client lifetime
                conn.connected_at = time.time()
                conn.caps = {k: True for k in
                             ("compress", "sg-replies", "trace-fops",
                              "shm-transport")
                             if (creds or {}).get(k)}
                try:
                    conn.opversion = int((creds or {}).get(
                        "op-version", 0))
                except (TypeError, ValueError):
                    conn.opversion = 0
                if not is_mgmt:
                    gf_event("CLIENT_CONNECT",
                             client=conn.identity.hex(),
                             brick=top.name, server=self.top.name,
                             addr=conn.peer_addr, subvol=want,
                             op_version=conn.opversion)
                return wire.MT_REPLY, {
                    "volume": top.name, "ok": True,
                    "compound": self._compound_on(top),
                    "sg": conn.sg,
                    "trace": self._trace_on(top),
                    # deadline-budget arming: this build pops the
                    # reserved request field before dispatch
                    "deadline": True,
                    # parity-delta write plane (op-version 12):
                    # this brick serves the xorv fop — a peer
                    # that never sees this key keeps the
                    # full-RMW path
                    "xorv": True,
                    # lease plane (op-version 15): this brick
                    # grants and recalls leases — a client that
                    # never sees this key must not enter zero-RT
                    # cache mode
                    "leases": True,
                    # same-host shared-memory bulk lane (op-version
                    # 17): a dict advert (boot-id + side-channel addr
                    # + one-shot token) for peers that asked, when the
                    # side-channel can hand out arena fds here — None
                    # otherwise (falsy = no lane, old clients ignore)
                    "shm": self._shm_advert(conn, creds or {}, top)}
            if not conn.authed:
                # SETVOLUME gates everything — pings included (no
                # pre-auth liveness probing; server.c refuses requests
                # from unknown clients)
                raise FopError(errno.EACCES, "handshake required")
            top = conn.top if conn.top is not None else self.top
            graph = conn.graph if conn.top is not None else self.graph
            if trace_id and tracing.ENABLED and self._trace_on(top):
                # re-arm the client's trace for this request's context:
                # every brick-graph span below carries the client's id
                # (frame->root across the wire)
                tracing.arm(str(trace_id))
            if fop_name == "__ping__":
                return wire.MT_REPLY, "pong"
            if fop_name == "__shm_ok__":
                # the client mapped both arenas and armed its rx side:
                # replies may now ride the s2c arena.  Arming strictly
                # follows the peer's readiness — no FL_SHM frame is
                # ever sent to an end that can't resolve it
                if conn.shm_tx is not None:
                    conn.shm_tx_armed = True
                conn.caps["shm"] = True
                return wire.MT_REPLY, {"ok": conn.shm_tx is not None}
            if fop_name == "__attach__":
                # brick-mux ATTACH (glusterfsd-mgmt.c:913): only the
                # ANCHOR graph's mgmt pair authorizes it — a volume's
                # own mgmt credential must stay scoped to that volume's
                # graph (reconfigure/statedump), never arbitrary-graph
                # execution or another volume's detach
                if not (conn.is_mgmt and conn.top is self.top):
                    raise FopError(errno.EACCES,
                                   "attach needs the anchor "
                                   "mgmt credential")
                name = await self.attach(args[0],
                                         args[1] if len(args) > 1
                                         else None)
                return wire.MT_REPLY, {"ok": True, "attached": name}
            if fop_name == "__detach__":
                if not (conn.is_mgmt and conn.top is self.top):
                    raise FopError(errno.EACCES,
                                   "detach needs the anchor "
                                   "mgmt credential")
                ok = await self.detach(args[0])
                return wire.MT_REPLY, {"ok": ok}
            if fop_name == "__status__":
                # deep-status brick half: glusterd fans this out per
                # node and merges (op_volume_status_local)
                kind = args[0] if args else "clients"
                return wire.MT_REPLY, _jsonable(
                    self._status_of(top, str(kind)))
            if fop_name == "__incident__":
                # incident fan-out brick half (glusterd
                # op_volume_incident_local): this process's flight
                # bundle — record ring + span ring + metrics — plus the
                # per-client accounting the bundle contract promises
                from ..core import flight

                bundle = flight.snapshot()
                try:
                    bundle["clients"] = self._status_of(top, "clients")
                except Exception as e:  # noqa: BLE001 - best-effort extra
                    bundle["clients"] = {"error": repr(e)[:200]}
                return wire.MT_REPLY, _jsonable(bundle)
            if fop_name == "__history__":
                # history fan-out brick half (ISSUE 20): this process's
                # sampled metrics ring, windowed by the caller
                from ..core import history

                window = float(args[0]) if args and args[0] else None
                return wire.MT_REPLY, _jsonable(
                    history.HISTORY.dump(window=window))
            if fop_name == "__alerts__":
                # alerts fan-out brick half (glusterd
                # op_volume_alerts_local): rules as configured, the
                # active set and recent RAISED/CLEARED transitions
                from ..core import slo

                return wire.MT_REPLY, _jsonable(slo.ENGINE.status())
            if fop_name == "__statedump__":
                # full-graph dump (has "layers") when the daemon handed
                # us the graph; bare top-layer dump otherwise
                src = graph if graph is not None else top
                return wire.MT_REPLY, _jsonable(src.statedump())
            if fop_name == "__reconfigure__":
                # live option apply from glusterd (xlator.reconfigure
                # path, graph.c glusterfs_graph_reconfigure); topology
                # changes need a daemon respawn instead
                if graph is None:
                    return wire.MT_REPLY, {"ok": False,
                                           "reason": "no graph handle"}
                ok = graph.apply_volfile(args[0])
                return wire.MT_REPLY, {"ok": ok}
            if fop_name in ("__compound__", "compound"):
                # the compound dispatcher: the whole chain executes
                # through the brick graph inside THIS request's single
                # backpressure slot (it was admitted as one fop), and
                # the client gets one reply frame carrying the per-link
                # vector.  A brick with compound-fops off refuses with
                # EOPNOTSUPP, which the client treats as "peer speaks
                # singles only" (mixed-version fallback).
                from ..rpc import compound as cfop

                if not self._compound_on(top):
                    raise FopError(errno.EOPNOTSUPP,
                                   "compound fops disabled")
                links = cfop.validate(conn.resolve(args[0] if args
                                                   else []))
                cnt = conn.fop_counts
                cnt["compound"] = cnt.get("compound", 0) + 1
                for _lf, largs, lkw in links:
                    cnt[_lf] = cnt.get(_lf, 0) + 1
                    _scope_owner(largs, lkw, conn.identity)
                wire.CURRENT_CLIENT.set(conn.identity)
                wire.CURRENT_LANE.set(self._lane_of(conn))
                # one handle-farm transaction per chain: batch the
                # posix sidecar journal around the WHOLE dispatch, so
                # the syscall coalescing holds even when a mid-graph
                # layer (locks, a cluster layer) decomposed the chain
                from contextlib import ExitStack

                from ..core.layer import walk

                with ExitStack() as stack:
                    for layer in walk(top):
                        jb = getattr(layer, "journal_batch", None)
                        if jb is not None:
                            stack.enter_context(jb())
                    replies = await top.compound(
                        links, (kwargs or {}).get("xdata"))
                return wire.MT_REPLY, [
                    [st, conn.wrap(val)] if st == "ok" else [st, val]
                    for st, val in replies]
            if fop_name not in _FOPS and fop_name not in _RPC_EXTRAS:
                raise FopError(errno.EOPNOTSUPP, f"unknown fop {fop_name!r}")
            conn.fop_counts[fop_name] = \
                conn.fop_counts.get(fop_name, 0) + 1
            fn = getattr(top, fop_name, None)
            if fn is None and fop_name in _RPC_EXTRAS:
                # extras (quota_usage, heal surfaces) live on a specific
                # mid-graph layer, not the passthrough top — resolve by
                # walking (the reference registers them as separate RPC
                # programs per xlator)
                from ..core.layer import walk

                for layer in walk(top):
                    fn = getattr(layer, fop_name, None)
                    if fn is not None:
                        break
            if fn is None:
                raise FopError(errno.EOPNOTSUPP, f"fop {fop_name!r} unsupported")
            # release retires the fd-table entry too (long-lived
            # connections like bitd's would otherwise grow it unboundedly)
            if fop_name == "release" and args and \
                    isinstance(args[0], wire.FdHandle):
                fd = conn.fds.pop(args[0].fdid, None)
                if fd is None:
                    return wire.MT_REPLY, {}
                await top.release(fd)
                return wire.MT_REPLY, {}
            args = conn.resolve(args)
            kwargs = {k: conn.resolve(v) for k, v in (kwargs or {}).items()}
            # scope lk-owners to this connection (cross-client isolation)
            _scope_owner(args, kwargs, conn.identity)
            # expose the peer identity to brick layers (frame->root->client)
            wire.CURRENT_CLIENT.set(conn.identity)
            wire.CURRENT_LANE.set(self._lane_of(conn))
            ret = fn(*args, **kwargs)
            if asyncio.iscoroutine(ret):
                ret = await ret
            return wire.MT_REPLY, conn.wrap(ret)
        except FopError as e:
            return wire.MT_ERROR, e
        except Exception as e:  # internal error: surface as EIO
            log.error(2, "dispatch error: %r", e)
            return wire.MT_ERROR, FopError(errno.EIO, f"internal: {e!r}")


def _scope_owner(args, kwargs, identity: bytes) -> None:
    """Prefix lk-owner with the connection identity so two clients using
    the same owner bytes don't alias (frame lk_owner + client uid).
    The owner riding a compound ``unlock-inodelk`` payload (the
    xattrop post-op + unlock fold) must be scoped identically, or the
    brick-side unlock would target an owner that never took the lock."""
    for container in list(args) + list(kwargs.values()):
        if not isinstance(container, dict):
            continue
        if "lk-owner" in container:
            container["lk-owner"] = identity + b"/" + container["lk-owner"]
        for key in ("unlock-inodelk", "lock-inodelk"):
            compound = container.get(key)
            if isinstance(compound, (list, tuple)) and len(compound) == 5:
                container[key] = [*compound[:4],
                                  identity + b"/" + compound[4]]


def _jsonable(v):
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v
