"""protocol/server — serves a brick graph over TCP.

Reference: xlators/protocol/server (actor table server-rpc-fops_v2.c:6132,
per-client fd tables + resolver, auth).  Here: an asyncio TCP service in
front of a layer graph.  Per-connection state mirrors ``client_t``: an fd
table (wire FdHandle -> live FdObj), the client's lk-owner prefix, and
disconnect cleanup that drops fds and lock grants (the reference's lock
reaping on disconnect).

Protocol: framed records (rpc/wire.py); a CALL carries
``[fop_name, args, kwargs]``; fd arguments travel as FdHandle; replies
carry the fop return (or MT_ERROR + FopError).  The handshake
(SETVOLUME analog) is the first call: ``__handshake__`` with the client
identity and requested subvolume name.
"""

from __future__ import annotations

import asyncio
from typing import Any

from ..core.fops import Fop, FopError
from ..core.layer import FdObj, Layer
from ..core import gflog
from ..rpc import wire

log = gflog.get_logger("protocol.server")

_FOPS = {f.value for f in Fop}
# non-wire-fop methods a client may invoke remotely (heal entry points,
# introspection — the reference exposes these via separate RPC programs)
_RPC_EXTRAS = {"heal_info", "heal_file", "heal_entry", "rebalance",
               "release", "getactivelk"}


class _ClientConn:
    def __init__(self, server: "BrickServer", writer: asyncio.StreamWriter):
        self.server = server
        self.writer = writer
        self.fds: dict[int, FdObj] = {}
        self.next_fd = 1
        self.identity = b""
        self.name = ""

    def register_fd(self, fd: FdObj) -> wire.FdHandle:
        fdid = self.next_fd
        self.next_fd += 1
        self.fds[fdid] = fd
        return wire.FdHandle(fdid, fd.gfid, fd.path)

    def resolve(self, v: Any) -> Any:
        if isinstance(v, wire.FdHandle):
            fd = self.fds.get(v.fdid)
            if fd is None:
                raise FopError(77, f"stale fd {v.fdid}")  # EBADFD
            return fd
        if isinstance(v, dict):
            if "__anon_fd__" in v:  # anonymous fd addressed by gfid
                return FdObj(v["__anon_fd__"], path=v.get("path", ""),
                             anonymous=True)
            return {k: self.resolve(x) for k, x in v.items()}
        if isinstance(v, list):
            return [self.resolve(x) for x in v]
        return v

    def wrap(self, v: Any) -> Any:
        if isinstance(v, FdObj):
            return self.register_fd(v)
        if isinstance(v, tuple):
            return [self.wrap(x) for x in v]
        if isinstance(v, list):
            return [self.wrap(x) for x in v]
        if isinstance(v, dict):
            return {k: self.wrap(x) for k, x in v.items()}
        return v


class BrickServer:
    """TCP service for one brick graph top (the brick process core)."""

    def __init__(self, top: Layer, host: str = "127.0.0.1", port: int = 0,
                 graph=None):
        self.top = top
        self.host = host
        self.port = port
        self.graph = graph  # enables live option reconfigure
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[_ClientConn] = set()

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        # hand the event-push callback to any upcall layer in the graph
        # (the reference's upcall xlator calls back through rpcsvc the
        # same way)
        from ..core.layer import walk

        for layer in walk(self.top):
            sink = getattr(layer, "set_upcall_sink", None)
            if sink is not None:
                sink(self.push_event)
        log.info(1, "brick %s serving on %s:%d", self.top.name, self.host,
                 self.port)
        return self.port

    def push_event(self, targets: list[bytes], payload: dict) -> None:
        """Send an MT_EVENT frame to each connected client in targets
        (xid 0: events correlate to no call)."""
        frame = wire.pack(0, wire.MT_EVENT, payload)
        for conn in list(self.connections):
            if conn.identity in targets:
                try:
                    conn.writer.write(frame)
                except Exception:
                    pass

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # close live connections too: since py3.12 wait_closed() also
            # waits for connection handlers, which would block forever on
            # clients that keep their sockets open
            for conn in list(self.connections):
                try:
                    conn.writer.close()
                except Exception:
                    pass
            await self._server.wait_closed()
            self._server = None

    # -- connection handling ----------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        conn = _ClientConn(self, writer)
        self.connections.add(conn)
        try:
            while True:
                try:
                    rec = await wire.read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                xid, mtype, payload = wire.unpack(rec)
                if mtype != wire.MT_CALL:
                    continue
                resp_type, resp = await self._dispatch(conn, payload)
                try:
                    writer.write(wire.pack(xid, resp_type, resp))
                    await writer.drain()
                except ConnectionError:
                    break
        finally:
            self.connections.discard(conn)
            await self._cleanup(conn)
            try:
                writer.close()
            except Exception:
                pass

    async def _cleanup(self, conn: _ClientConn) -> None:
        """Disconnect: release fds + this client's locks (client_t reap)."""
        for fd in conn.fds.values():
            rel = getattr(self.top, "release", None)
            if rel is not None:
                try:
                    await rel(fd)
                except Exception:
                    pass
        conn.fds.clear()
        if conn.identity:
            from ..core.layer import walk

            for layer in walk(self.top):
                rc = getattr(layer, "release_client", None)
                if rc is not None:
                    try:
                        rc(conn.identity)
                    except Exception:
                        pass

    async def _dispatch(self, conn: _ClientConn, payload: Any):
        try:
            fop_name, args, kwargs = payload
            if fop_name == "__handshake__":
                conn.identity = args[0]
                conn.name = args[1] if len(args) > 1 else ""
                return wire.MT_REPLY, {"volume": self.top.name, "ok": True}
            if fop_name == "__ping__":
                return wire.MT_REPLY, "pong"
            if fop_name == "__statedump__":
                # full-graph dump (has "layers") when the daemon handed
                # us the graph; bare top-layer dump otherwise
                src = self.graph if self.graph is not None else self.top
                return wire.MT_REPLY, _jsonable(src.statedump())
            if fop_name == "__reconfigure__":
                # live option apply from glusterd (xlator.reconfigure
                # path, graph.c glusterfs_graph_reconfigure); topology
                # changes need a daemon respawn instead
                if self.graph is None:
                    return wire.MT_REPLY, {"ok": False,
                                           "reason": "no graph handle"}
                ok = self.graph.apply_volfile(args[0])
                return wire.MT_REPLY, {"ok": ok}
            if fop_name not in _FOPS and fop_name not in _RPC_EXTRAS:
                raise FopError(95, f"unknown fop {fop_name!r}")
            fn = getattr(self.top, fop_name, None)
            if fn is None:
                raise FopError(95, f"fop {fop_name!r} unsupported")
            # release retires the fd-table entry too (long-lived
            # connections like bitd's would otherwise grow it unboundedly)
            if fop_name == "release" and args and \
                    isinstance(args[0], wire.FdHandle):
                fd = conn.fds.pop(args[0].fdid, None)
                if fd is None:
                    return wire.MT_REPLY, {}
                await self.top.release(fd)
                return wire.MT_REPLY, {}
            args = conn.resolve(args)
            kwargs = {k: conn.resolve(v) for k, v in (kwargs or {}).items()}
            # scope lk-owners to this connection (cross-client isolation)
            _scope_owner(args, kwargs, conn.identity)
            # expose the peer identity to brick layers (frame->root->client)
            wire.CURRENT_CLIENT.set(conn.identity)
            ret = fn(*args, **kwargs)
            if asyncio.iscoroutine(ret):
                ret = await ret
            return wire.MT_REPLY, conn.wrap(ret)
        except FopError as e:
            return wire.MT_ERROR, e
        except Exception as e:  # internal error: surface as EIO
            log.error(2, "dispatch error: %r", e)
            return wire.MT_ERROR, FopError(5, f"internal: {e!r}")


def _scope_owner(args, kwargs, identity: bytes) -> None:
    """Prefix lk-owner with the connection identity so two clients using
    the same owner bytes don't alias (frame lk_owner + client uid)."""
    for container in list(args) + list(kwargs.values()):
        if isinstance(container, dict) and "lk-owner" in container:
            container["lk-owner"] = identity + b"/" + container["lk-owner"]


def _jsonable(v):
    if isinstance(v, bytes):
        return v.hex()
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v
