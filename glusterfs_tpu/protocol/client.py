"""protocol/client — the fop->RPC bridge layer with failure detection.

Reference: xlators/protocol/client (client.c:171 client_submit_request,
client-handshake.c SETVOLUME, rpc-clnt-ping.c heartbeat).  A Layer whose
every fop serializes to the wire and whose connection state drives
CHILD_UP / CHILD_DOWN notifications:

* connect + handshake -> CHILD_UP
* ping every ``ping-interval``; no pong within ``ping-timeout`` ->
  disconnect -> CHILD_DOWN (rpc-clnt-ping.c:125 semantics)
* auto-reconnect with backoff (rpc_clnt reconnect timer)
* in-flight calls fail with ENOTCONN on disconnect (saved_frames unwind,
  rpc-clnt.c:198)

Fd objects map to server-side FdHandles kept in the local fd ctx.
"""

from __future__ import annotations

import asyncio
import errno
import itertools
from typing import Any

from ..core.fops import Fop, FopError
from ..core.iatt import gfid_new
from ..core.layer import Event, FdObj, Layer, register
from ..core.options import Option
from ..core import gflog
from ..rpc import wire

log = gflog.get_logger("protocol.client")


@register("protocol/client")
class ClientLayer(Layer):
    OPTIONS = (
        Option("remote-host", "str", default="127.0.0.1"),
        Option("remote-port", "int", default=0),
        Option("remote-subvolume", "str", default=""),
        Option("ping-interval", "time", default="1"),
        Option("ping-timeout", "time", default="5",
               description="declare peer dead after this (network.ping-timeout)"),
        Option("reconnect-interval", "time", default="0.5"),
        Option("call-timeout", "time", default="30"),
        Option("username", "str", default="",
               description="login credential presented at SETVOLUME "
                           "(volgen injects the volume's generated pair)"),
        Option("password", "str", default=""),
        Option("ssl", "bool", default="off",
               description="TLS to the brick (client.ssl / socket.c)"),
        Option("ssl-ca", "str", default="",
               description="CA bundle to verify the brick cert against"),
        Option("ssl-cert", "str", default="",
               description="client certificate (mutual TLS)"),
        Option("ssl-key", "str", default=""),
        Option("compression", "bool", default="off",
               description="zlib on-wire frames (the cdc/compress "
                           "xlator analog); the brick mirrors it on "
                           "replies after the handshake"),
        Option("compression-min-size", "size", default="512",
               description="frames below this ship uncompressed"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.connected = False
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._xid = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._tasks: list[asyncio.Task] = []
        self._closing = False
        self.identity = gfid_new()
        self._last_pong = 0.0

    # -- lifecycle ---------------------------------------------------------

    async def init(self):
        await super().init()
        self._closing = False
        self._tasks.append(asyncio.create_task(self._connect_loop()))

    async def fini(self):
        self._closing = True
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        await self._drop_connection(notify=False)
        await super().fini()

    async def _connect_loop(self) -> None:
        while not self._closing:
            if not self.connected:
                try:
                    await self._connect()
                except Exception as e:
                    log.debug(3, "%s: connect failed: %r", self.name, e)
            await asyncio.sleep(self.opts["reconnect-interval"])

    def _ssl_context(self):
        if not self.opts["ssl"]:
            return None
        from ..rpc import tls

        return tls.client_context(self.opts["ssl-ca"],
                                  self.opts["ssl-cert"],
                                  self.opts["ssl-key"])

    async def _connect(self) -> None:
        host = self.opts["remote-host"]
        port = self.opts["remote-port"]
        # reap finished read-loop tasks from failed attempts
        self._tasks = [t for t in self._tasks if not t.done()]
        reader, writer = await asyncio.open_connection(
            host, port, ssl=self._ssl_context())
        self._reader, self._writer = reader, writer
        self._tasks.append(asyncio.create_task(self._read_loop(reader)))
        # handshake = SETVOLUME (client-handshake.c) with auth/login
        # credentials (client_setvolume req dict auth keys)
        creds = {}
        if self.opts["username"]:
            creds = {"username": self.opts["username"],
                     "password": self.opts["password"]}
        if self.opts["compression"]:
            creds["compress"] = True
        try:
            res = await self._call("__handshake__",
                                   (self.identity,
                                    self.opts["remote-subvolume"], creds),
                                   {})
        except BaseException:
            await self._drop_connection(notify=False)
            raise
        if not res.get("ok"):
            # close NOW: the retry loop would otherwise leak one socket
            # + read task per attempt on both ends
            await self._drop_connection(notify=False)
            raise FopError(errno.EACCES,
                           res.get("error", "handshake rejected"))
        self.connected = True
        loop = asyncio.get_running_loop()
        self._last_pong = loop.time()
        self._tasks.append(asyncio.create_task(self._ping_loop()))
        log.info(4, "%s: connected to %s:%d (%s)", self.name, host, port,
                 res.get("volume"))
        self.notify(Event.CHILD_UP, None, None)

    async def _drop_connection(self, notify: bool = True) -> None:
        was = self.connected
        self.connected = False
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
            self._reader = None
        # unwind in-flight calls (saved_frames analog)
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(FopError(errno.ENOTCONN, "disconnected"))
        self._pending.clear()
        if was and notify:
            log.warning(5, "%s: disconnected", self.name)
            self.notify(Event.CHILD_DOWN, None, None)

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                rec = await wire.read_frame(reader)
                xid, mtype, payload = wire.unpack(rec)
                if mtype == wire.MT_EVENT:
                    # server-pushed upcall (cache invalidation etc.):
                    # surface as a graph notification for md-cache & co
                    self.notify(Event.UPCALL, None, payload)
                    continue
                fut = self._pending.pop(xid, None)
                if fut is None or fut.done():
                    continue
                if mtype == wire.MT_ERROR:
                    fut.set_exception(payload if isinstance(payload, FopError)
                                      else FopError(errno.EIO, str(payload)))
                else:
                    fut.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            if reader is self._reader:
                await self._drop_connection()

    async def _ping_loop(self) -> None:
        loop = asyncio.get_running_loop()
        interval = self.opts["ping-interval"]
        timeout = self.opts["ping-timeout"]
        try:
            while self.connected:
                t0 = loop.time()
                await asyncio.sleep(interval)
                # a LOCAL event-loop stall (host overload, long compile)
                # silences our own ping clock — don't blame the peer
                # for it (rpc-clnt-ping only counts time the transport
                # was actually serviced)
                stalled = loop.time() - t0 > 3 * interval
                try:
                    await asyncio.wait_for(
                        self._call("__ping__", (), {}), interval)
                    self._last_pong = loop.time()
                except (FopError, asyncio.TimeoutError):
                    pass
                if stalled:
                    self._last_pong = max(self._last_pong,
                                          loop.time() - interval)
                    continue
                if loop.time() - self._last_pong > timeout:
                    log.warning(6, "%s: ping timeout (%.1fs)", self.name,
                                timeout)
                    await self._drop_connection()
                    return
        except asyncio.CancelledError:
            pass

    # -- call machinery ----------------------------------------------------

    async def _call(self, fop: str, args: tuple, kwargs: dict) -> Any:
        writer = self._writer
        if writer is None:
            raise FopError(errno.ENOTCONN, f"{self.name}: not connected")
        xid = next(self._xid)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[xid] = fut
        try:
            body = [fop, list(args), kwargs or {}]
            if self.opts["compression"]:
                frame = wire.pack_z(xid, wire.MT_CALL, body,
                                    int(self.opts[
                                        "compression-min-size"]))
            else:
                frame = wire.pack(xid, wire.MT_CALL, body)
            writer.write(frame)
            await writer.drain()
        except (ConnectionError, RuntimeError):
            self._pending.pop(xid, None)
            await self._drop_connection()
            raise FopError(errno.ENOTCONN, "send failed") from None
        try:
            return await asyncio.wait_for(fut, self.opts["call-timeout"])
        except asyncio.TimeoutError:
            self._pending.pop(xid, None)
            raise FopError(errno.ETIMEDOUT, f"{fop} timed out") from None

    def _wire_args(self, args: tuple) -> tuple:
        out = []
        for a in args:
            if isinstance(a, FdObj):
                h = a.ctx_get(self)
                if h is None:
                    # anonymous fd: address by gfid server-side
                    out.append({"__anon_fd__": a.gfid, "path": a.path})
                else:
                    out.append(h)
            else:
                out.append(a)
        return tuple(out)

    async def fop_call(self, name: str, *args, **kwargs) -> Any:
        if not self.connected:
            raise FopError(errno.ENOTCONN, f"{self.name}: child down")
        ret = await self._call(name, self._wire_args(args), kwargs)
        return self._absorb(ret, args)

    def _absorb(self, ret: Any, args: tuple) -> Any:
        """Turn returned FdHandles into local FdObjs."""
        if isinstance(ret, wire.FdHandle):
            fd = FdObj(ret.gfid, path=ret.path)
            fd.ctx_set(self, ret)
            return fd
        if isinstance(ret, list):
            return [self._absorb(x, args) for x in ret]
        return ret

    async def release(self, fd: FdObj) -> None:
        h = fd.ctx_del(self)
        if h is not None and self.connected:
            try:
                await self._call("release", (h,), {})
            except FopError:
                pass

    # remote admin/heal entry points (separate RPC programs in reference)
    async def remote(self, method: str, *args, **kwargs) -> Any:
        return await self.fop_call(method, *args, **kwargs)

    async def statedump_remote(self) -> dict:
        return await self._call("__statedump__", (), {})

    def dump_private(self) -> dict:
        return {"connected": self.connected,
                "remote": f"{self.opts['remote-host']}:"
                          f"{self.opts['remote-port']}",
                "pending_calls": len(self._pending)}


def _make_wire_fop(op_name: str):
    async def wired(self, *args, **kwargs):
        ret = await self.fop_call(op_name, *args, **kwargs)
        return ret
    wired.__name__ = op_name
    return wired


for _fop in Fop:
    setattr(ClientLayer, _fop.value, _make_wire_fop(_fop.value))
