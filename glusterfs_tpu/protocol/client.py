"""protocol/client — the fop->RPC bridge layer with failure detection.

Reference: xlators/protocol/client (client.c:171 client_submit_request,
client-handshake.c SETVOLUME, rpc-clnt-ping.c heartbeat).  A Layer whose
every fop serializes to the wire and whose connection state drives
CHILD_UP / CHILD_DOWN notifications:

* connect + handshake -> CHILD_UP
* ping every ``ping-interval``; no pong within ``ping-timeout`` ->
  disconnect -> CHILD_DOWN (rpc-clnt-ping.c:125 semantics)
* auto-reconnect with backoff (rpc_clnt reconnect timer)
* in-flight calls fail with ENOTCONN on disconnect (saved_frames unwind,
  rpc-clnt.c:198)
* on reconnect, every tracked open fd is RE-OPENED server-side and held
  locks are re-acquired BEFORE CHILD_UP is announced
  (client-handshake.c:30,68-97 client_reopen_done /
  client_child_up_reopen_done, reopen_fd_count) — a long-lived fd
  against a bounced brick keeps working instead of silently degrading
  that brick out of every fop until the file is re-opened.

Fd objects map to server-side FdHandles kept in the local fd ctx.
"""

from __future__ import annotations

import asyncio
import errno
import itertools
import os
from typing import Any

from ..core.events import gf_event
from ..core.fops import Fop, FopError
from ..core.iatt import gfid_new
from ..core.layer import Event, FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import flight, gflog, tracing
from ..core import metrics as _metrics
from ..rpc import shm as _shm
from ..rpc import wire
from ..rpc import event_pool as _evt

log = gflog.get_logger("protocol.client")

# live client layers, scraped by the unified registry (weakref): the
# client half of the wire accounting — per-connection bytes match the
# brick's per-client counters from the other end of the same socket
_LIVE_CLIENT_LAYERS = _metrics.REGISTRY.register_objects(
    "gftpu_client_wire_bytes_total", "counter",
    "wire bytes exchanged by each protocol/client connection",
    lambda l: [({"layer": l.name, "dir": "tx"}, l.bytes_tx),
               ({"layer": l.name, "dir": "rx"}, l.bytes_rx)])
_metrics.REGISTRY.register_objects(
    "gftpu_client_reconnects_total", "counter",
    "successful SETVOLUME handshakes per protocol/client (first "
    "connect counts as one)",
    lambda l: [({"layer": l.name}, l.connects)],
    live=_LIVE_CLIENT_LAYERS)

# failure-containment plane (ISSUE 9): per-brick circuit state, the
# idempotent-retry volume, and failfast transport bails — the health
# plane's view of which bricks are shedding load
_CB_STATES = {"closed": 0, "open": 1, "half-open": 2}
_metrics.REGISTRY.register_objects(
    "gftpu_client_circuit_state", "gauge",
    "per-brick circuit breaker state (0 closed / 1 open / 2 half-open)",
    lambda l: [({"layer": l.name}, _CB_STATES.get(l._cb_state, 0))],
    live=_LIVE_CLIENT_LAYERS)
_metrics.REGISTRY.register_objects(
    "gftpu_client_retries_total", "counter",
    "idempotent fops re-dispatched after a transport-class failure "
    "(capped exponential backoff through the circuit breaker)",
    lambda l: [({"layer": l.name}, l.retries_total)],
    live=_LIVE_CLIENT_LAYERS)
_metrics.REGISTRY.register_objects(
    "gftpu_client_failfast_total", "counter",
    "call-timeout transport bails: the connection was dropped so every "
    "other outstanding frame failed NOW instead of serially waiting "
    "out its own deadline",
    lambda l: [({"layer": l.name}, l.failfast_drops)],
    live=_LIVE_CLIENT_LAYERS)
_metrics.REGISTRY.register_objects(
    "gftpu_qos_client_backoff_total", "counter",
    "fops re-sent after a brick qos-throttle shed (the client half of "
    "the QoS plane: the caller sees a slower fop, never the EAGAIN)",
    lambda l: [({"layer": l.name}, l.qos_backoff_total)],
    live=_LIVE_CLIENT_LAYERS)


@register("protocol/client")
class ClientLayer(Layer):
    OPTIONS = (
        Option("remote-host", "str", default="127.0.0.1"),
        Option("remote-port", "int", default=0),
        Option("remote-subvolume", "str", default=""),
        Option("ping-interval", "time", default="1"),
        Option("ping-timeout", "time", default="5",
               description="declare peer dead after this (network.ping-timeout)"),
        Option("reconnect-interval", "time", default="0.5"),
        Option("call-timeout", "time", default="30"),
        Option("username", "str", default="",
               description="login credential presented at SETVOLUME "
                           "(volgen injects the volume's generated pair)"),
        Option("password", "str", default=""),
        Option("ssl", "bool", default="off",
               description="TLS to the brick (client.ssl / socket.c)"),
        Option("ssl-ca", "str", default="",
               description="CA bundle to verify the brick cert against"),
        Option("ssl-cert", "str", default="",
               description="client certificate (mutual TLS)"),
        Option("ssl-key", "str", default=""),
        Option("event-threads", "int", default=2, min=0, max=64,
               description="reply-turning workers "
                           "(client.event-threads; the client half "
                           "of the multithreaded-epoll analog): "
                           "decode of large reply frames — a 4 MiB "
                           "scatter-gather readv reply, a fat "
                           "readdirp listing — moves off the read "
                           "loop onto the process-wide event pool, "
                           "so it no longer serializes behind the "
                           "next request's encode.  The pool is "
                           "shared by every protocol/client in the "
                           "process (the reference's per-process "
                           "gf-event pool); connect grows it to the "
                           "largest configured value, reconfigure "
                           "applies the new value exactly.  0 = "
                           "decode inline (pre-9 behavior)"),
        Option("compound-fops", "bool", default="off",
               description="fuse chained fops into single wire frames "
                           "(cluster.use-compound-fops); only engages "
                           "when the brick advertised compound support "
                           "at SETVOLUME — otherwise chains decompose "
                           "into singles (mixed-version fallback)"),
        Option("sg-replies", "bool", default="on",
               description="request scatter-gather reply payloads at "
                           "SETVOLUME (network.zero-copy-reads): a "
                           "reply held brick-side as several buffers "
                           "arrives as a blob vector decoded into "
                           "segment views — no join copy on either "
                           "end.  Off = the brick joins before "
                           "framing (pre-sg wire behavior)"),
        Option("shm-transport", "bool", default="on",
               description="arm the same-host shared-memory bulk lane "
                           "at SETVOLUME when the brick advertises it "
                           "(network.shm-transport client half, "
                           "rpc/shm): request payloads (writev/xorv/"
                           "compound blobs) are written once into a "
                           "memfd arena shared with the brick and only "
                           "descriptors ride the socket; reply blobs "
                           "arrive as views into the peer's arena.  "
                           "Read per-call: off live-downgrades to "
                           "inline frames without a reconnect"),
        Option("trace-fops", "bool", default="on",
               description="ship the current trace id as a trailing "
                           "wire-frame field so brick-side spans join "
                           "the client's trace "
                           "(diagnostics.trace-propagation); only "
                           "engages when the brick advertised trace "
                           "support at SETVOLUME — a live-downgraded "
                           "peer simply never sees the field"),
        Option("circuit-breaker", "bool", default="on",
               description="per-brick circuit breaking "
                           "(client.circuit-breaker): after "
                           "circuit-failure-threshold consecutive "
                           "transport-class failures (ENOTCONN / "
                           "ETIMEDOUT) the circuit OPENS — fops fail "
                           "immediately instead of feeding a flapping "
                           "brick a retry storm; after "
                           "circuit-reset-interval it half-opens and "
                           "admits ONE probe, whose outcome closes or "
                           "re-opens it.  A successful SETVOLUME "
                           "handshake always closes the circuit"),
        Option("circuit-failure-threshold", "int", default=5, min=1,
               max=1024,
               description="consecutive transport failures that open "
                           "the circuit (client.circuit-failure-"
                           "threshold)"),
        Option("circuit-reset-interval", "time", default="2",
               description="open -> half-open probe delay "
                           "(client.circuit-reset-interval)"),
        Option("failfast", "bool", default="on",
               description="a fop round-trip hitting call-timeout "
                           "drops the transport (the frame-timeout "
                           "bail): every other outstanding frame "
                           "fails with ENOTCONN NOW instead of each "
                           "serially waiting out its own deadline "
                           "against a peer that eats requests.  Lock "
                           "fops are exempt — they park server-side "
                           "legitimately"),
        Option("idempotent-retries", "int", default=2, min=0, max=8,
               description="re-dispatch attempts for idempotent "
                           "(read-class) fops after a transport-class "
                           "failure, with capped exponential backoff; "
                           "retries stop the moment the circuit opens "
                           "(client.idempotent-retries; the georep "
                           "repce retry allowlist idea on the data "
                           "plane).  0 = fail through immediately"),
        Option("retry-backoff-max", "time", default="1",
               description="cap on the exponential retry backoff "
                           "(base 50ms, doubling per attempt)"),
        Option("qos-backoff", "bool", default="on",
               description="honor brick qos-throttle notices "
                           "(client.qos-backoff): a frame shed by the "
                           "brick's QoS admission (EAGAIN + retry-after "
                           "in the error xdata) is re-sent after the "
                           "advertised wait instead of surfacing the "
                           "errno — safe for ANY fop, idempotent or "
                           "not, because a shed frame was refused at "
                           "admission and never dispatched.  Off = the "
                           "raw EAGAIN (+ notice) reaches the caller"),
        Option("deadline-propagation", "bool", default="on",
               description="ship each fop's remaining deadline budget "
                           "in the request (network.deadline-"
                           "propagation): the brick arms it per "
                           "request so io-threads can DROP work whose "
                           "client already timed the call out instead "
                           "of burning a worker on an abandoned "
                           "answer.  Only engages when the brick "
                           "advertised the capability at SETVOLUME"),
        Option("strict-locks", "bool", default="off",
               description="fds holding posix locks must not be "
                           "reached through anonymous (gfid-addressed) "
                           "fds after a reconnect dropped their "
                           "server-side handle (client.strict-locks, "
                           "reference client.c:2438): lock-protected "
                           "I/O fails with EBADFD instead of silently "
                           "bypassing the lock's fd identity"),
        Option("compression", "bool", default="off",
               description="zlib on-wire frames (the cdc/compress "
                           "xlator analog); the brick mirrors it on "
                           "replies after the handshake"),
        Option("compression-min-size", "size", default="512",
               description="frames below this ship uncompressed"),
        Option("compression-level", "int", default=1, min=-1, max=9,
               description="zlib level for on-wire compression "
                           "(network.compression.compression-level)"),
        # socket.c transport knobs (0 = kernel default)
        Option("tcp-user-timeout", "time", default="0",
               description="TCP_USER_TIMEOUT: cap on unacked-data "
                           "linger before the kernel declares the "
                           "peer dead (client.tcp-user-timeout)"),
        Option("keepalive-time", "time", default="20",
               description="TCP_KEEPIDLE (client.keepalive-time)"),
        Option("keepalive-interval", "time", default="2",
               description="TCP_KEEPINTVL (client.keepalive-interval)"),
        Option("keepalive-count", "int", default=9, min=0,
               description="TCP_KEEPCNT (client.keepalive-count)"),
        Option("tcp-window-size", "size", default="0",
               description="SO_RCVBUF/SO_SNDBUF "
                           "(network.tcp-window-size)"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.connected = False
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._xid = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._tasks: list[asyncio.Task] = []
        self._closing = False
        self.identity = gfid_new()
        self._last_pong = 0.0
        # did the peer advertise compound support at SETVOLUME?
        self._peer_compound = False
        # did the peer advertise trace-span re-arming at SETVOLUME?
        self._peer_trace = False
        # fop round-trips awaited on this transport (handshake/ping
        # excluded; the wire-frame-counting tests read this)
        self.rpc_roundtrips = 0
        # wire accounting (client half of the brick's per-client
        # counters): integer adds on buffers already in hand
        self.bytes_tx = 0
        self.bytes_rx = 0
        self.connects = 0
        # circuit breaker (client.circuit-breaker): closed -> open on
        # consecutive transport failures -> half-open probe -> closed
        self._cb_state = "closed"
        self._cb_failures = 0
        self._cb_opened_at = 0.0
        self._cb_probing = False
        self.retries_total = 0
        self.failfast_drops = 0
        # QoS plane (features/qos): traffic attribution carried in the
        # handshake creds ("rebalance" rides the brick's paced lane;
        # set by api.Client/mount_volume BEFORE connect so the first
        # handshake already carries it), and the shed-retry count
        self.traffic_origin = ""
        self.qos_backoff_total = 0
        # did the brick advertise deadline-budget arming at SETVOLUME?
        self._peer_deadline = False
        # did the brick advertise the xorv fop (parity-delta writes)?
        self._peer_xorv = False
        # did the brick advertise lease grants (op-version 15)?  The
        # api layer checks this before letting caches go zero-RT
        self._peer_leases = False
        # same-host shared-memory bulk lane (rpc/shm, op-version 17):
        # armed at SETVOLUME via the brick's fd side-channel.  _peer_shm
        # flips only after BOTH arenas mapped and the brick confirmed
        # (__shm_ok__); _shm_refused remembers a brick-side EOPNOTSUPP
        # downgrade (like the xorv memory — zero wasted frames after)
        self._peer_shm = False
        self._shm_tx = None
        self._shm_rx = None
        self._shm_refused = False
        _LIVE_CLIENT_LAYERS.add(self)
        # reopen bookkeeping (client-handshake.c reopen_fd_count):
        # live fds with server-side handles (value = (fd, reopen fop)),
        # and locks granted through this connection, replayed on
        # reconnect before CHILD_UP
        self._fds: dict[int, tuple[FdObj, str]] = {}
        self._held_locks: dict[tuple, tuple] = {}  # key -> (fop, args, kw)

    def reconfigure(self, options: dict) -> None:
        """client.event-threads applies live: the process-wide reply
        pool is resized to the operator's latest value exactly —
        grow AND shrink (the connect-time path only grows it)."""
        before = self.opts["event-threads"]
        super().reconfigure(options)
        after = self.opts["event-threads"]
        if after != before:
            _evt.client_pool_resize(after)

    # -- lifecycle ---------------------------------------------------------

    async def init(self):
        await super().init()
        self._closing = False
        self._tasks.append(asyncio.create_task(self._connect_loop()))

    async def fini(self):
        self._closing = True
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()
        await self._drop_connection(notify=False)
        await super().fini()

    async def _connect_loop(self) -> None:
        while not self._closing:
            if not self.connected:
                try:
                    await self._connect()
                except Exception as e:
                    log.debug(3, "%s: connect failed: %r", self.name, e)
            await asyncio.sleep(self.opts["reconnect-interval"])

    def _ssl_context(self):
        if not self.opts["ssl"]:
            return None
        from ..rpc import tls

        return tls.client_context(self.opts["ssl-ca"],
                                  self.opts["ssl-cert"],
                                  self.opts["ssl-key"])

    async def _connect(self) -> None:
        host = self.opts["remote-host"]
        port = self.opts["remote-port"]
        # reap finished read-loop tasks from failed attempts
        self._tasks = [t for t in self._tasks if not t.done()]
        reader, writer = await asyncio.open_connection(
            host, port, ssl=self._ssl_context())
        from ..rpc.socktune import tune_socket

        tune_socket(writer.get_extra_info("socket"),
                    keepalive_time=self.opts["keepalive-time"],
                    keepalive_interval=self.opts["keepalive-interval"],
                    keepalive_count=self.opts["keepalive-count"],
                    user_timeout=self.opts["tcp-user-timeout"],
                    window_size=self.opts["tcp-window-size"])
        self._reader, self._writer = reader, writer
        self._tasks.append(asyncio.create_task(self._read_loop(reader)))
        # handshake = SETVOLUME (client-handshake.c) with auth/login
        # credentials (client_setvolume req dict auth keys)
        creds = {}
        if self.opts["username"]:
            creds = {"username": self.opts["username"],
                     "password": self.opts["password"]}
        # advertise this build's op-version (client_setvolume sends
        # GD_OP_VERSION the same way) and the trace willingness, for
        # the brick's client accounting (client_t capability column)
        from .. import OP_VERSION

        creds["op-version"] = OP_VERSION
        if self.traffic_origin:
            # QoS traffic attribution (features/qos): re-sent on every
            # reconnect handshake, so attribution survives a bounce
            creds["origin"] = self.traffic_origin
        if self.opts["trace-fops"]:
            creds["trace-fops"] = True
        if self.opts["compression"]:
            creds["compress"] = True
        if self.opts["sg-replies"] and not self.opts["compression"]:
            # sg only pays off on the blob lane; compressed frames
            # inline everything anyway
            creds["sg-replies"] = True
        if self.opts["shm-transport"] and not self.opts["compression"] \
                and not self._shm_refused and _shm.supported():
            # ask for the shared-memory bulk lane (same
            # compression carve-out as sg: inlined frames carry no
            # blobs for the arena to hold)
            creds["shm-transport"] = True
        try:
            res = await self._call("__handshake__",
                                   (self.identity,
                                    self.opts["remote-subvolume"], creds),
                                   {})
        except BaseException:
            await self._drop_connection(notify=False)
            raise
        if not res.get("ok"):
            # close NOW: the retry loop would otherwise leak one socket
            # + read task per attempt on both ends
            await self._drop_connection(notify=False)
            raise FopError(errno.EACCES,
                           res.get("error", "handshake rejected"))
        # per-peer capability (mixed-version clusters): a brick that
        # doesn't advertise compound gets singles from this client
        self._peer_compound = bool(res.get("compound"))
        # did the peer advertise trace re-arming?  The local trace-fops
        # option is read per-call (not folded in here) so a live
        # volume-set of diagnostics.trace-propagation applies without
        # a reconnect — same pattern as compound-fops
        self._peer_trace = bool(res.get("trace"))
        # deadline-budget propagation: only to bricks that pop the
        # reserved request field before dispatch (older bricks would
        # pass it into the fop signature)
        self._peer_deadline = bool(res.get("deadline"))
        # parity-delta writes: only bricks that serve xorv (op-version
        # 12).  A missing key fails the fop EOPNOTSUPP locally — zero
        # round trips wasted per write against a live-downgraded brick
        self._peer_xorv = bool(res.get("xorv"))
        # lease plane: only bricks that grant + recall leases (op-
        # version 15).  A client stack over an older brick never enters
        # zero-RT cache mode — TTL revalidation stays the coherence
        # story there
        self._peer_leases = bool(res.get("leases"))
        # shm bulk lane: the advert carries boot-id + side-channel
        # address + one-shot token.  Arming failure of ANY kind is the
        # boring fallback — this connection simply stays inline
        ad = res.get("shm")
        if ad and creds.get("shm-transport"):
            try:
                await self._shm_arm(ad)
            except Exception as e:  # noqa: BLE001 - fallback is total
                log.warning(8, "%s: shm lane arming failed: %r",
                            self.name, e)
                _shm.count_fallback("sidechannel")
                self._shm_teardown()
        # re-open tracked fds and re-acquire held locks BEFORE CHILD_UP
        # (client_child_up_reopen_done): parents must never see an "up"
        # child whose fd handles are stale
        try:
            await self._reopen_fds()
            await self._reacquire_locks()
        except BaseException:
            await self._drop_connection(notify=False)
            raise
        self.connected = True
        self.connects += 1
        # a successful SETVOLUME is transport proof: the circuit closes
        # (the probe path for reconnect-driven recovery)
        self._cb_record(True)
        loop = asyncio.get_running_loop()
        self._last_pong = loop.time()
        self._tasks.append(asyncio.create_task(self._ping_loop()))
        log.info(4, "%s: connected to %s:%d (%s)", self.name, host, port,
                 res.get("volume"))
        # events.h EVENT_BRICK_CONNECTED — fires on every successful
        # SETVOLUME, so a reconnect storm is visible as a pulse train
        gf_event("BRICK_CONNECTED", layer=self.name,
                 brick=str(res.get("volume", "")),
                 remote=f"{host}:{port}",
                 subvol=self.opts["remote-subvolume"])
        self.notify(Event.CHILD_UP, None, None)

    async def _reopen_fds(self) -> None:
        """Re-open every tracked fd on the fresh connection
        (client_reopen_done, client-handshake.c:68-97).  A file that
        vanished while we were away drops its handle — the fd degrades
        to gfid-addressed (anonymous) access and surfaces ENOENT
        naturally on the next fop."""
        import os as _os

        for key, (fd, how) in list(self._fds.items()):
            loc = Loc(fd.path, gfid=fd.gfid)
            # never replay creation semantics: O_TRUNC would wipe the
            # file we are reconnecting to, O_CREAT|O_EXCL would EEXIST
            flags = fd.flags & ~(_os.O_CREAT | _os.O_EXCL | _os.O_TRUNC)
            fop_args = (loc,) if how == "opendir" else (loc, flags)
            try:
                ret = await self._call(how, fop_args, {})
            except FopError as e:
                log.warning(8, "%s: reopen of %s failed: %s", self.name,
                            fd.path or fd.gfid.hex(), e)
                fd.ctx_del(self)
                self._fds.pop(key, None)
                continue
            if isinstance(ret, wire.FdHandle):
                fd.ctx_set(self, ret)
            log.debug(8, "%s: reopened %s", self.name,
                      fd.path or fd.gfid.hex())

    async def _reacquire_locks(self) -> None:
        """Replay granted locks on the fresh brick (the brick restarted
        with empty lock tables).  Bounded per lock: a now-conflicting
        lock (someone else grabbed the range while we were away) is
        dropped with a warning — the reference's lk-heal gives these up
        after its grace period too."""
        for key, (fop, args, kwargs) in list(self._held_locks.items()):
            try:
                await asyncio.wait_for(
                    self._call(fop, self._wire_args(args), dict(kwargs)),
                    5)
            except (FopError, asyncio.TimeoutError) as e:
                log.warning(8, "%s: lost %s lock across reconnect: %r",
                            self.name, fop, e)
                self._held_locks.pop(key, None)

    async def _shm_arm(self, ad: dict) -> None:
        """Arm the shared-memory bulk lane from a SETVOLUME advert:
        boot-id screen, side-channel fd exchange (the real same-host
        proof — the fds either map or they don't), then __shm_ok__ so
        the brick knows replies may ride its s2c arena.  The rx arena
        is armed BEFORE __shm_ok__ goes out: no FL_SHM reply can beat
        our ability to resolve it."""
        if str(ad.get("boot-id", "")) != _shm.boot_id():
            # different machine: the side-channel cannot exist here —
            # don't even dial (cheap screen; lane never arms)
            _shm.count_fallback("cross-host")
            return
        addr = str(ad.get("addr") or "")
        token = str(ad.get("token") or "")
        if not addr or not token:
            _shm.count_fallback("sidechannel")
            return
        # blocking AF_UNIX dial + SCM_RIGHTS receive, off the loop
        fds = await asyncio.to_thread(_shm.fetch_fds, addr, token)
        try:
            self._shm_tx = _shm.ShmTx.attach(fds[0])   # c2s: we write
            self._shm_rx = _shm.ShmRx.attach(fds[1])   # s2c: we read
        finally:
            for fd in fds:
                try:
                    os.close(fd)
                except OSError:
                    pass
        res = await self._call("__shm_ok__", (), {})
        if not (isinstance(res, dict) and res.get("ok")):
            raise FopError(errno.EPROTO, "shm confirm refused")
        self._peer_shm = True
        log.info(8, "%s: shm bulk lane armed", self.name)

    def _shm_teardown(self) -> None:
        """Drop both arenas (close defers under live consumer views);
        the lane re-arms on the next successful handshake unless
        refused."""
        self._peer_shm = False
        for arena in (self._shm_tx, self._shm_rx):
            if arena is not None:
                try:
                    arena.close()
                except Exception:
                    pass
        self._shm_tx = None
        self._shm_rx = None

    def _shm_disarm(self, reason: str) -> None:
        """Peer-driven downgrade (EOPNOTSUPP + shm-unsupported xdata):
        remembered like the xorv capability — this layer never offers
        shm again, so zero further frames are wasted on it."""
        self._shm_refused = True
        _shm.count_fallback(reason)
        self._shm_teardown()
        log.warning(8, "%s: shm lane disarmed (%s)", self.name, reason)
        flight.record("shm_disarm", layer=self.name, reason=reason)

    async def _drop_connection(self, notify: bool = True) -> None:
        was = self.connected
        self.connected = False
        self._shm_teardown()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
            self._reader = None
        # unwind in-flight calls (saved_frames analog)
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(FopError(errno.ENOTCONN, "disconnected"))
        self._pending.clear()
        if was and notify:
            log.warning(5, "%s: disconnected", self.name)
            gf_event("BRICK_DISCONNECTED", layer=self.name,
                     remote=f"{self.opts['remote-host']}:"
                            f"{self.opts['remote-port']}",
                     subvol=self.opts["remote-subvolume"])
            self.notify(Event.CHILD_DOWN, None, None)

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                rec = await wire.read_frame(reader)
                self.bytes_rx += len(rec) + 4  # + the length prefix
                # reply turning (client.event-threads): large frames
                # decode on the shared event pool, keyed by this layer
                # so one connection's replies and upcalls resolve in
                # arrival order; small frames decode inline (cheaper
                # than the handoff).  A layer configured to 0 decodes
                # inline even when another graph grew the shared pool
                # (the documented escape hatch is per-volume)
                n = self.opts["event-threads"]
                pool = _evt.client_pool(n) \
                    if n > 0 and len(rec) >= _evt.TURN_MIN else None
                if pool is not None and pool.size > 0:
                    xid, mtype, payload = await pool.turn(
                        self, wire.unpack, rec, self._shm_rx)
                else:
                    xid, mtype, payload = wire.unpack(rec, self._shm_rx)
                if mtype == wire.MT_EVENT:
                    # server-pushed upcall (cache invalidation etc.):
                    # surface as a graph notification for md-cache & co
                    self.notify(Event.UPCALL, None, payload)
                    continue
                fut = self._pending.pop(xid, None)
                if fut is None or fut.done():
                    continue
                if mtype == wire.MT_ERROR:
                    fut.set_exception(payload if isinstance(payload, FopError)
                                      else FopError(errno.EIO, str(payload)))
                else:
                    fut.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            if reader is self._reader:
                await self._drop_connection()

    async def _ping_loop(self) -> None:
        loop = asyncio.get_running_loop()
        interval = self.opts["ping-interval"]
        timeout = self.opts["ping-timeout"]
        try:
            while self.connected:
                t0 = loop.time()
                await asyncio.sleep(interval)
                # a LOCAL event-loop stall (host overload, long compile)
                # silences our own ping clock — don't blame the peer
                # for it (rpc-clnt-ping only counts time the transport
                # was actually serviced)
                stalled = loop.time() - t0 > 3 * interval
                try:
                    await asyncio.wait_for(
                        self._call("__ping__", (), {}), interval)
                    self._last_pong = loop.time()
                except (FopError, asyncio.TimeoutError):
                    pass
                if stalled:
                    self._last_pong = max(self._last_pong,
                                          loop.time() - interval)
                    continue
                if loop.time() - self._last_pong > timeout:
                    log.warning(6, "%s: ping timeout (%.1fs)", self.name,
                                timeout)
                    await self._drop_connection()
                    return
        except asyncio.CancelledError:
            pass

    # -- circuit breaker (client.circuit-breaker) --------------------------

    #: failures that indict the TRANSPORT (not the fop): these trip the
    #: breaker and are the only errors the idempotent allowlist retries
    _TRANSPORT_ERRNOS = (errno.ENOTCONN, errno.ETIMEDOUT)

    @classmethod
    def _is_transport_err(cls, e: FopError) -> bool:
        """Did this failure indict the transport?  ENOTCONN always
        does; ETIMEDOUT only when the CLIENT's own deadline expired
        (``_local_timeout`` stamped in _call) — a server-ANSWERED
        ETIMEDOUT (a contended lock wait, an io-threads deadline drop)
        proves the wire as well as OK does, and must not open the
        circuit for a healthy brick."""
        if e.err == errno.ENOTCONN:
            return True
        return e.err == errno.ETIMEDOUT and \
            getattr(e, "_local_timeout", False)

    def _cb_admit(self) -> bool:
        """Gate one fop through the breaker: open fails fast (load
        shedding — a flapping brick must not absorb a retry storm),
        open past the reset interval half-opens and admits exactly ONE
        probe, half-open with a probe in flight fails fast.  Returns
        True when THIS call is the half-open probe (the caller must
        clear ``_cb_probing`` if it aborts without an outcome)."""
        if not self.opts["circuit-breaker"] or self._cb_state == "closed":
            return False
        if self._cb_state == "open":
            now = asyncio.get_running_loop().time()
            if now - self._cb_opened_at < \
                    self.opts["circuit-reset-interval"]:
                raise FopError(errno.ENOTCONN,
                               f"{self.name}: circuit open")
            self._cb_state = "half-open"
            self._cb_probing = False
        if self._cb_probing:
            raise FopError(errno.ENOTCONN,
                           f"{self.name}: circuit half-open "
                           "(probe in flight)")
        self._cb_probing = True
        return True

    def _cb_record(self, transport_ok: bool) -> None:
        """Account one fop outcome.  ``transport_ok`` means the wire
        answered (success or an ordinary fop error — ENOENT proves the
        transport as well as OK does)."""
        if not self.opts["circuit-breaker"]:
            return
        if transport_ok:
            self._cb_failures = 0
            self._cb_probing = False
            if self._cb_state != "closed":
                self._cb_state = "closed"
                log.info(6, "%s: circuit closed", self.name)
                gf_event("CLIENT_CIRCUIT_CLOSE", layer=self.name,
                         remote=f"{self.opts['remote-host']}:"
                                f"{self.opts['remote-port']}",
                         subvol=self.opts["remote-subvolume"])
            return
        self._cb_failures += 1
        self._cb_probing = False
        threshold = int(self.opts["circuit-failure-threshold"])
        if self._cb_state == "half-open" or \
                self._cb_failures >= threshold:
            try:
                self._cb_opened_at = asyncio.get_running_loop().time()
            except RuntimeError:
                return  # no loop: stay put rather than wedge open
            if self._cb_state != "open":
                self._cb_state = "open"
                log.warning(6, "%s: circuit OPEN after %d consecutive "
                            "transport failures", self.name,
                            self._cb_failures)
                gf_event("CLIENT_CIRCUIT_OPEN", layer=self.name,
                         failures=self._cb_failures,
                         remote=f"{self.opts['remote-host']}:"
                                f"{self.opts['remote-port']}",
                         subvol=self.opts["remote-subvolume"])

    # -- call machinery ----------------------------------------------------

    @staticmethod
    def _load_headroom() -> float:
        """Deadline multiplier for blocking lock fops, scaled to host
        load.  A blocking inodelk legitimately parks server-side for up
        to the locks layer's lock-timeout (30s default) — the same value
        as call-timeout — so on a loaded single-core host the RPC
        deadline races the server's own wait and loses by scheduling
        jitter alone ("inodelk timed out" full-suite flake, VERDICT r5
        weak #5).  Floor 2x so the race can't tie even on an idle host;
        cap 8x so a genuinely dead brick still fails in bounded time."""
        try:
            import os as _os

            load = _os.getloadavg()[0] / (_os.cpu_count() or 1)
        except (OSError, AttributeError):
            load = 1.0
        return min(8.0, max(2.0, load))

    async def _call(self, fop: str, args: tuple, kwargs: dict) -> Any:
        writer = self._writer
        if writer is None:
            raise FopError(errno.ENOTCONN, f"{self.name}: not connected")
        data_fop = fop == "__compound__" or not fop.startswith("__")
        if data_fop:
            self.rpc_roundtrips += 1
        timeout = self.opts["call-timeout"]
        if fop in self._LOCK_FOPS:
            timeout *= self._load_headroom()
        elif data_fop and self._peer_deadline and \
                self.opts["deadline-propagation"]:
            # ship the remaining budget (relative seconds — clocks
            # differ across processes) so brick-side io-threads can
            # drop work this call will have abandoned by the time a
            # worker frees up (the reserved field is popped by the
            # brick before dispatch; gated on the SETVOLUME capability)
            kwargs = {**(kwargs or {}), "__deadline__": round(timeout, 3)}
        xid = next(self._xid)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[xid] = fut
        lane = None
        try:
            body = [fop, list(args), kwargs or {}]
            if self._peer_trace and tracing.ENABLED and \
                    self.opts["trace-fops"]:
                # trailing trace-id element (the wire twin of the
                # reference's frame->root): the server re-arms it so
                # brick-graph spans carry THIS request's trace id.
                # Handshake/ping frames predate _peer_trace or carry no
                # fop context worth attributing.
                tid = tracing.current_id()
                if tid is not None:
                    body.append(tid)
            if self.opts["compression"]:
                buf = wire.pack_z(
                    xid, wire.MT_CALL, body,
                    int(self.opts["compression-min-size"]),
                    self.opts["compression-level"])
                self.bytes_tx += len(buf)
                writer.write(buf)
            else:
                # payload blobs ride out-of-band and writelines hands
                # the ORIGINAL buffers to the transport — a writev
                # payload is never copied on this side (iobref submit).
                # With the shm lane armed (and the option still on —
                # read per-call, so a live volume-set downgrades
                # instantly), blobs land in the shared arena and only
                # descriptors cross the socket
                if self._peer_shm and self._shm_tx is not None \
                        and not self._shm_tx.dead \
                        and self.opts["shm-transport"]:
                    lane = self._shm_tx
                frames = wire.pack_frames(xid, wire.MT_CALL, body, lane)
                self.bytes_tx += sum(len(f) for f in frames)
                writer.writelines(frames)
            await writer.drain()
        except (ConnectionError, RuntimeError):
            self._pending.pop(xid, None)
            await self._drop_connection()
            raise FopError(errno.ENOTCONN, "send failed") from None
        try:
            return await asyncio.wait_for(fut, timeout)
        except FopError as e:
            if lane is not None \
                    and isinstance(getattr(e, "xdata", None), dict) \
                    and e.xdata.get("shm-unsupported"):
                # the brick can't serve our shm frames (live downgrade,
                # restarted peer, lost mapping): remember the refusal
                # like the xorv capability and resend THIS call inline
                # — the caller never sees the downgrade
                self._shm_disarm("downgrade")
                return await self._call(fop, args, kwargs)
            raise
        except asyncio.TimeoutError:
            self._pending.pop(xid, None)
            if data_fop and fop not in self._LOCK_FOPS and \
                    self.opts["failfast"]:
                # frame-timeout bail (disconnect failfast): a peer that
                # ate a whole call deadline is treated as dead — drop
                # the transport so every OTHER outstanding frame fails
                # ENOTCONN now instead of serially waiting out its own
                # deadline.  Lock fops are exempt (they park
                # server-side legitimately); the reconnect loop takes
                # over from here.
                self.failfast_drops += 1
                log.warning(6, "%s: %s hit call-timeout (%.0fs) — "
                            "bailing the transport", self.name, fop,
                            timeout)
                flight.record("failfast_drop", layer=self.name, fop=fop,
                              timeout_s=round(float(timeout), 3))
                await self._drop_connection()
            e = FopError(errno.ETIMEDOUT, f"{fop} timed out")
            # the CLIENT's deadline expired — the wire never answered.
            # The breaker distinguishes this from a server-returned
            # ETIMEDOUT (which proves the transport)
            e._local_timeout = True
            raise e from None

    # payloads at or above this ride the out-of-band blob lane; below
    # it the tagged codec's inline copy is cheaper than a second iovec
    BLOB_MIN = 4096

    def _fd_holds_locks(self, fd: FdObj) -> bool:
        """Does this fd hold posix locks granted through this
        connection?  (lk / fd-addressed inodelk-class grants are keyed
        by the fd's identity in the replay table.)  id() keys cannot
        alias a recycled object: every entry's value tuple holds the
        fd itself (args), so the fd outlives its keys."""
        return any(k[1] == id(fd) for k in self._held_locks)

    def _strict_lock_check(self, args: tuple) -> None:
        """client.strict-locks (client.c:2438): an fd whose server-side
        handle is gone but which holds posix locks must NOT be silently
        served via an anonymous fd — the anon route bypasses the fd
        identity the lock protects (another client could have been
        granted the range while we were away).  Lock fops themselves
        are exempt: the unlock that clears the record must always be
        able to go out."""
        if not self.opts["strict-locks"]:
            return
        for a in args:
            if isinstance(a, FdObj) and not a.anonymous and \
                    a.ctx_get(self) is None and self._fd_holds_locks(a):
                raise FopError(
                    errno.EBADFD,
                    "fd holds locks but lost its remote handle "
                    "(strict-locks)")

    def _wire_args(self, args: tuple) -> tuple:
        out = []
        for a in args:
            if isinstance(a, FdObj):
                h = a.ctx_get(self)
                if h is None:
                    # anonymous fd: address by gfid server-side
                    out.append({"__anon_fd__": a.gfid, "path": a.path})
                else:
                    out.append(h)
            elif isinstance(a, (bytes, bytearray, memoryview)) and \
                    len(a) >= self.BLOB_MIN:
                out.append(wire.Blob(a))
            else:
                out.append(a)
        return tuple(out)

    _LOCK_FOPS = ("inodelk", "finodelk", "entrylk", "fentrylk", "lk")

    #: fops safe to re-dispatch after a transport-class failure (the
    #: georep repce allowlist idea on the data plane): read-class only —
    #: a duplicated read is harmless, a duplicated write is not
    _IDEMPOTENT_FOPS = frozenset((
        "lookup", "stat", "fstat", "access", "readlink", "readv",
        "getxattr", "fgetxattr", "statfs", "readdir", "readdirp",
        "seek", "rchecksum"))

    # qos-backoff retry ceiling: with a sane brick config the advertised
    # retry-after drains the bucket debt in a few rounds; the cap only
    # guards against a pathological advert spinning the loop forever
    _QOS_RETRY_CAP = 64

    async def fop_call(self, name: str, *args, **kwargs) -> Any:
        """One fop through the breaker, with the idempotent-retry loop:
        read-class fops re-dispatch after transport-class failures with
        capped exponential backoff (base 50ms, doubling), but never
        past an OPEN circuit — load shedding beats persistence on a
        flapping brick."""
        attempt = 0
        shaped = 0
        while True:
            try:
                return await self._fop_call_once(name, *args, **kwargs)
            except FopError as e:
                note = (getattr(e, "xdata", None) or {}).get(
                    "qos-throttle")
                if note is not None and e.err == errno.EAGAIN and \
                        self.opts["qos-backoff"] and not self._closing \
                        and shaped < self._QOS_RETRY_CAP:
                    # brick QoS shed (features/qos): refused at
                    # admission, never dispatched — so retrying is safe
                    # for ANY fop, not just idempotent ones.  The wait
                    # comes from the brick's own bucket math; the
                    # backoff cap bounds a misconfigured advert.  This
                    # loop IS the client-side shaping: the caller just
                    # sees a slower fop, never the errno.
                    shaped += 1
                    self.qos_backoff_total += 1
                    delay = min(float(self.opts["retry-backoff-max"]),
                                max(float(note.get("retry-after") or 0),
                                    0.005))
                    await asyncio.sleep(delay)
                    continue
                if not self._is_transport_err(e) or \
                        name not in self._IDEMPOTENT_FOPS or \
                        self._closing or self._cb_state == "open" or \
                        attempt >= int(self.opts["idempotent-retries"]):
                    raise
                attempt += 1
                self.retries_total += 1
                delay = min(float(self.opts["retry-backoff-max"]),
                            0.05 * (1 << (attempt - 1)))
                log.debug(8, "%s: retrying %s after %r (attempt %d, "
                          "%.2fs backoff)", self.name, name, e, attempt,
                          delay)
                await asyncio.sleep(delay)

    async def _fop_call_once(self, name: str, *args, **kwargs) -> Any:
        try:
            probe = self._cb_admit()
        except FopError:
            if name in self._LOCK_FOPS:
                # same contract as the not-connected path: a shed
                # unlock must still drop its replay entry
                self._track_lock(name, args, kwargs, failed=True)
            raise
        if not self.connected:
            if name in self._LOCK_FOPS:
                # a failed UNLOCK must still drop the replay entry: the
                # server reaps this client's locks on disconnect and the
                # caller proceeds as released — replaying it on
                # reconnect would pin a lock nobody will ever drop
                self._track_lock(name, args, kwargs, failed=True)
            self._cb_record(False)
            raise FopError(errno.ENOTCONN, f"{self.name}: child down")
        try:
            if name not in self._LOCK_FOPS:
                self._strict_lock_check(args)
            ret = await self._call(name, self._wire_args(args), kwargs)
        except FopError as e:
            self._cb_record(not self._is_transport_err(e))
            if name in self._LOCK_FOPS:
                self._track_lock(name, args, kwargs, failed=True)
                note = (getattr(e, "xdata", None)
                        or {}).get("lock-revoked")
                if note:
                    # the brick revoked our lock(s): purge the replay
                    # set for that domain, or reconnect would resurrect
                    # a lock the containment plane just broke
                    self._forget_revoked(note)
            raise
        except BaseException:
            # an aborted probe (cancellation, encode error) has no
            # outcome to record — release the half-open slot or the
            # breaker wedges in "probe in flight" forever
            if probe:
                self._cb_probing = False
            raise
        self._cb_record(True)
        out = self._absorb(ret, args)
        if name in ("open", "create", "opendir"):
            self._note_fd_result(name, out, args)
        elif name in ("inodelk", "finodelk", "entrylk", "fentrylk", "lk"):
            self._track_lock(name, args, kwargs)
        elif name in ("xattrop", "fxattrop"):
            # compound post-op unlock (features/locks xdata): the brick
            # released the lock — drop it from the replay set too, or a
            # reconnect would resurrect it forever
            unlock = (kwargs.get("xdata") or {}).get("unlock-inodelk")
            if unlock:
                domain, _ltype, start, end, owner = unlock
                target = args[0]
                ident = id(target) if isinstance(target, FdObj) else \
                    (target.gfid or target.path)
                okey = owner.hex() if isinstance(owner,
                                                 (bytes, bytearray)) \
                    else str(owner)
                for lkname in ("inodelk", "finodelk"):
                    self._held_locks.pop(
                        (lkname, ident, domain, okey, start, end), None)
        return out

    def _note_fd_result(self, name: str, out: Any, args: tuple) -> None:
        """Remember a just-opened fd (+ flags and the fop that re-creates
        it) for the reconnect re-open; create returns (fd, iatt) so walk
        one level of the absorbed result."""
        flat = out if isinstance(out, (list, tuple)) else (out,)
        for fd in flat:
            if isinstance(fd, FdObj) and fd.ctx_get(self) is not None:
                if name != "opendir":
                    fd.flags = next((a for a in args[1:]
                                     if isinstance(a, int)), fd.flags)
                self._fds[id(fd)] = (
                    fd, "opendir" if name == "opendir" else "open")

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Ship a whole chain as ONE wire frame (the tentpole fusion:
        create+writev+flush+release of a small file is a single round
        trip).  Decomposes into ordinary wired fops when the volume key
        is off, the peer didn't advertise compound at SETVOLUME, or the
        chain carries lock fops (their reconnect-replay bookkeeping
        lives in fop_call)."""
        from ..rpc import compound as cfop

        links = cfop.validate(links)
        if not (self.connected and self.opts["compound-fops"]
                and self._peer_compound) or \
                any(l[0] in self._LOCK_FOPS for l in links):
            return await cfop.decompose(self, links, xdata)
        wire_links = []
        for fop, args, kwargs in links:
            self._strict_lock_check(args)
            wargs = [{cfop.FD_LINK_KEY: a.index}
                     if isinstance(a, cfop.FdRef) else a
                     for a in self._wire_args(args)]
            wkw = {k: ({cfop.FD_LINK_KEY: v.index}
                       if isinstance(v, cfop.FdRef) else v)
                   for k, v in kwargs.items()}
            wire_links.append([fop, wargs, wkw])
        probe = self._cb_admit()
        try:
            replies = await self._call(
                "__compound__", (wire_links,),
                {"xdata": xdata} if xdata else {})
        except FopError as e:
            self._cb_record(not self._is_transport_err(e))
            if e.err in (errno.ENOSYS, errno.EOPNOTSUPP):
                # the brick was downgraded/reconfigured under us:
                # remember and fall back to singles for this connection
                self._peer_compound = False
                return await cfop.decompose(self, links, xdata)
            raise
        except BaseException:
            if probe:  # aborted probe: release the half-open slot
                self._cb_probing = False
            raise
        self._cb_record(True)
        out = []
        for entry, (fop, args, _kw) in zip(replies, links):
            st, val = entry[0], entry[1]
            if st == "ok":
                val = self._absorb(val, args)
                if fop in cfop.FD_PRODUCERS:
                    self._note_fd_result(fop, val, args)
            out.append([st, val])
        return out

    async def xorv(self, fd: FdObj, data, offset: int,
                   xdata: dict | None = None):
        """Parity-delta apply (ISSUE 10).  Capability-gated: a brick
        that did not advertise ``xorv`` at SETVOLUME (op-version < 12,
        or live-downgraded under us) fails EOPNOTSUPP HERE, without a
        round trip — the EC layer treats that as "peer speaks full RMW
        only" and falls back.  Write-class: deliberately NOT in the
        idempotent-retry allowlist (a replayed XOR self-cancels)."""
        if self.connected and not self._peer_xorv:
            raise FopError(errno.EOPNOTSUPP,
                           f"{self.name}: peer has no xorv "
                           "(pre-op-version-12 brick)")
        kwargs = {"xdata": xdata} if xdata is not None else {}
        try:
            return await self.fop_call("xorv", fd, data, offset,
                                       **kwargs)
        except FopError as e:
            if e.err in (errno.EOPNOTSUPP, errno.ENOSYS):
                # reconfigured/downgraded brick answered: remember so
                # later writes skip the wasted round trip
                self._peer_xorv = False
                raise FopError(errno.EOPNOTSUPP, str(e)) from None
            raise

    def _forget_revoked(self, note: dict) -> None:
        """A 'lock-revoked' notice arrived on a lock fop's EAGAIN
        (features.locks-revocation): drop every replay entry in that
        lock domain — the brick already broke them, and the strict-locks
        pairing means lock-protected I/O on those fds fails loudly
        rather than riding a lock that no longer exists.  Dropping only
        weakens reconnect replay, never correctness."""
        domain = note.get("domain")
        kind = note.get("kind")
        for key in list(self._held_locks):
            if kind == "posix":
                if key[0] == "lk":
                    self._held_locks.pop(key, None)
            elif domain is not None and len(key) > 2 and \
                    key[2] == domain:
                self._held_locks.pop(key, None)

    def _track_lock(self, name: str, args: tuple, kwargs: dict,
                    failed: bool = False) -> None:
        """Mirror granted/released locks for reconnect replay.  Keys
        lead with the lock target's identity so release() can drop a
        closing fd's record locks in one sweep.  ``failed``: the call
        errored — unlocks still forget the entry (see fop_call), grants
        are never recorded."""

        def owner_of(xd):
            o = (xd or {}).get("lk-owner")
            return o.hex() if isinstance(o, (bytes, bytearray)) else str(o)

        def ident(target):
            if isinstance(target, FdObj):
                return id(target)
            return target.gfid or target.path

        try:
            if name in ("inodelk", "finodelk"):
                domain, target, cmd = args[0], args[1], args[2]
                start = args[4] if len(args) > 4 else kwargs.get("start", 0)
                end = args[5] if len(args) > 5 else kwargs.get("end", -1)
                xd = args[6] if len(args) > 6 else kwargs.get("xdata")
                key = (name, ident(target), domain, owner_of(xd),
                       start, end)
            elif name in ("entrylk", "fentrylk"):
                domain, target, basename = args[0], args[1], args[2]
                cmd = args[3]
                xd = args[5] if len(args) > 5 else kwargs.get("xdata")
                key = (name, ident(target), domain, basename,
                       owner_of(xd))
            else:  # lk
                fd, cmd, flock = args[0], args[1], args[2]
                if cmd == "getlk":
                    return
                xd = args[3] if len(args) > 3 else kwargs.get("xdata")
                key = ("lk", id(fd), owner_of(xd),
                       flock.get("start", 0), flock.get("len", 0))
                cmd = "unlock" if flock.get("type") == "unlck" else "lock"
            if cmd in ("lock", "lock-nb") and not failed:
                self._held_locks[key] = (name, args, kwargs)
            elif cmd not in ("lock", "lock-nb"):
                self._held_locks.pop(key, None)
        except (IndexError, AttributeError, TypeError):
            pass  # unexpected call shape: tracking must never break fops

    def _absorb(self, ret: Any, args: tuple) -> Any:
        """Turn returned FdHandles into local FdObjs and scatter-gather
        vectors into SGBufs (segments are memoryviews into the reply
        frame — the payload is never joined on this side either)."""
        if isinstance(ret, wire.FdHandle):
            fd = FdObj(ret.gfid, path=ret.path)
            fd.ctx_set(self, ret)
            return fd
        if isinstance(ret, dict) and len(ret) == 1 and \
                isinstance(ret.get(wire.SG_KEY), list):
            # the segment list shape is part of the marker: a user
            # xattr dict that merely has the key must pass untouched
            return wire.SGBuf(ret[wire.SG_KEY])
        if isinstance(ret, list):
            return [self._absorb(x, args) for x in ret]
        return ret

    async def release(self, fd: FdObj) -> None:
        self._fds.pop(id(fd), None)
        # a closed fd's record locks die with it (POSIX close semantics)
        self._held_locks = {k: v for k, v in self._held_locks.items()
                            if k[1] != id(fd)}
        h = fd.ctx_del(self)
        if h is not None and self.connected and self._writer is not None:
            # fire-and-forget, but ON THE WIRE NOW: release carries no
            # status the caller can observe (close() already returned
            # flush's) and the server reaps fd tables on disconnect —
            # yet the frame must hit the transport before any later
            # fop's, or a subsequent lock request could reach the brick
            # ahead of the release that frees the range it wants.  The
            # reply (matched by xid) finds no pending future and is
            # dropped by the read loop.
            xid = next(self._xid)
            try:
                frames = wire.pack_frames(
                    xid, wire.MT_CALL, ["release", [h], {}])
                self.bytes_tx += sum(len(f) for f in frames)
                self._writer.writelines(frames)
            except (ConnectionError, RuntimeError):
                pass  # teardown race: the server reaps on disconnect

    # remote admin/heal entry points (separate RPC programs in reference)
    async def remote(self, method: str, *args, **kwargs) -> Any:
        return await self.fop_call(method, *args, **kwargs)

    async def statedump_remote(self) -> dict:
        return await self._call("__statedump__", (), {})

    def dump_private(self) -> dict:
        return {"connected": self.connected,
                "remote": f"{self.opts['remote-host']}:"
                          f"{self.opts['remote-port']}",
                "pending_calls": len(self._pending),
                "bytes_tx": self.bytes_tx,
                "bytes_rx": self.bytes_rx,
                "connects": self.connects,
                "rpc_roundtrips": self.rpc_roundtrips,
                "shm": {"armed": self._peer_shm,
                        "refused": self._shm_refused,
                        "tx_used": (self._shm_tx.used()
                                    if self._shm_tx is not None else 0),
                        "rx_held": (self._shm_rx.used()
                                    if self._shm_rx is not None else 0)}}


def _make_wire_fop(op_name: str):
    async def wired(self, *args, **kwargs):
        ret = await self.fop_call(op_name, *args, **kwargs)
        return ret
    wired.__name__ = op_name
    return wired


from ..core.layer import _timed as _layer_timed  # noqa: E402

for _fop in Fop:
    # explicit methods (compound: capability-gated fusion + fallback)
    # keep their implementation; everything else is a plain wired fop.
    # Wrapped with the layer timer: protocol/client's per-fop stats ARE
    # the wire round-trip latency (the p50/p99 the bench records), and
    # the timed bracket is what mints/joins the trace span here when
    # this layer is the graph top.
    if _fop.value not in vars(ClientLayer):
        setattr(ClientLayer, _fop.value,
                _layer_timed(_fop.value, _make_wire_fop(_fop.value)))
