"""glusterfs_tpu: a TPU-native scale-out storage framework.

Brand-new implementation of the capabilities of the reference distributed
storage system (GlusterFS, mounted read-only at /root/reference): translator
graphs over bricks, hash distribution, replication, Reed-Solomon erasure
coding, self-heal, management plane and client APIs — with all GF(256)
erasure-coding compute batched onto TPU via JAX/XLA/Pallas.
"""

__version__ = "0.1.0"

# This build's management op-version (xlator.h:758 / GD_OP_VERSION):
# peers advertise theirs at probe time and the cluster operates at the
# minimum, gating newer volume-set keys until every member upgrades.
# Lives here (not in mgmt/glusterd) so protocol/client can advertise it
# at SETVOLUME without dragging the whole management plane into every
# client process.  Version history: 19 history + SLO alerting plane
# (per-process metrics history ring core/history.py + the declarative
# SLO engine core/slo.py, diagnostics.history-* / diagnostics.slo-rules
# keys, the __history__/__alerts__ brick doors and glusterd's
# volume-alerts fan-out, volgen._V19_KEYS);
# 18 incident plane (per-process
# flight recorder core/flight.py + auto-capture diagnostics.incident-*
# keys, the __incident__ brick RPC and glusterd's cluster capture
# fan-out, the gateway's --incident-dir spawner arm, volgen._V18_KEYS);
# 17 same-host shared-memory bulk
# lane (memfd arena transport rpc/shm, the "shm" SETVOLUME capability,
# network.shm-transport + network.shm-arena-size, volgen._V17_KEYS);
# 16 multi-tenant QoS plane
# (per-client token buckets + priority lanes at the brick's frame
# admission, server.qos-* + client.qos-backoff, the gateway's --qos-*
# spawner arm, volgen._V16_KEYS); 15 lease plane (brick-side lease
# grants/recalls advertised as the "leases" SETVOLUME capability,
# features.lease-timeout idle expiry + the gateway's lease-held object
# cache gateway.object-cache-size, volgen._V15_KEYS); 14 multi-process
# data plane
# (gateway.workers shared-nothing worker pool + cluster.mesh-distributed
# jax.distributed brick mesh, volgen._V14_KEYS; also lifts the
# mesh-codec-vs-systematic mutual exclusion — the mesh tier gained a
# parity-rows-only systematic encode); 13 managed rebalance daemon
# (volume rebalance start/status/stop ops + rebalance-update RPC +
# rebalance.checkpoint-interval / cluster.rebal-migrate-window,
# volgen._V13_KEYS); 12 parity-delta write plane (the
# brick-side xorv fop + cluster.delta-writes, volgen._V12_KEYS; also
# the cluster floor for volgen's systematic-by-default disperse
# layout); 11 failure-containment plane (lock
# revocation features.locks-revocation-*, client circuit breaking +
# idempotent retries + deadline propagation, debug.error-failure-count,
# volgen._V11_KEYS); 10 mesh-sharded codec data plane
# (cluster.mesh-codec, volgen._V10_KEYS); 9 concurrent event plane
# (server/client.event-threads frame-turning pools + the reader/
# writer-split fuse bridge, _V9_KEYS); 8 HTTP object gateway
# keys (_V8_KEYS); 7 observability (trace propagation + slow-fop
# diagnostics, _V7_KEYS); 6 zero-copy reads + strict-locks (_V6_KEYS);
# 5 compound fops + auth.ssl-allow (_V5_KEYS); 4 round-5 keys
# (_V4_KEYS); 3 the round-4 option long tail (_V3_KEYS).
OP_VERSION = 19
