"""glusterfs_tpu: a TPU-native scale-out storage framework.

Brand-new implementation of the capabilities of the reference distributed
storage system (GlusterFS, mounted read-only at /root/reference): translator
graphs over bricks, hash distribution, replication, Reed-Solomon erasure
coding, self-heal, management plane and client APIs — with all GF(256)
erasure-coding compute batched onto TPU via JAX/XLA/Pallas.
"""

__version__ = "0.1.0"
