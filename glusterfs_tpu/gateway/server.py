"""The object gateway daemon: asyncio HTTP/1.1 over pooled glfs.

Dialect (S3-flavored; JSON where S3 speaks XML — docs/object_gateway.md
has the full tour):

    GET    /                       list buckets
    PUT    /bucket                 create bucket (top-level directory)
    DELETE /bucket                 remove bucket (must be empty -> 409)
    GET    /bucket?list&prefix=&marker=&max-keys=&delimiter=
                                   list objects (sorted, marker paging,
                                   delimiter -> common_prefixes)
    PUT    /bucket/key             write object (ETag: sha256 content
                                   hash, the checksum layer's strong
                                   digest, persisted as an xattr)
    GET    /bucket/key             read object; ``Range: bytes=`` gives
                                   206 served as SGBuf segments written
                                   straight to the socket (no join)
    HEAD   /bucket/key             stat + ETag, no body
    DELETE /bucket/key             unlink

Keys may contain ``/`` — they map to nested directories under the
bucket, which is what makes ``delimiter=/`` listing a single readdir.

Concurrency model: every HTTP connection is one asyncio task; fops
multiplex onto a small :class:`ClientPool` of mounted
:class:`api.glfs.Client` graphs (the pooled-glfs-handle analog of how
NFS-Ganesha shares a few glfs_t among many NFS clients).  Admission
control is connection-granular: past ``max_clients`` live connections
the gateway answers 503 and emits ``GATEWAY_CLIENT_THROTTLED``.  When
glusterd's spawner passes the volume's ``server.qos-*`` rates it is
ALSO request-granular: per-peer-IP token buckets (features/qos,
``door="gateway"``) answer 429 + ``Retry-After`` on overdraft — HTTP
clients inherit the same per-identity shaping the brick applies on the
wire, and a lease-held object-cache hit is exempt from the fops bucket
(zero wire fops; QoS never recalls a lease just to shape).

Zero-copy GET path: ranged reads ride
:meth:`api.glfs.Client.read_file`'s raw window — wire blob views /
io-cache page views arrive as :class:`rpc.wire.SGBuf` segments and go
to the socket via ``StreamWriter.writelines`` with the response head
prepended, so the payload is never joined in the gateway
(``gftpu_gateway_body_writes_total{shape="sg"}`` counts the proof).
"""

from __future__ import annotations

import asyncio
import errno
import hashlib
import itertools
import json
import os
import time
import urllib.parse
from typing import Any, AsyncIterator, Callable

from ..api.glfs import Client
from ..core import events as gf_events
from ..core import flight, gflog, tracing
from ..core.fops import FopError
from ..core.metrics import REGISTRY, LogHistogram, labeled
from ..performance import cache_metrics
from ..rpc.wire import SGBuf, as_single_buffer

log = gflog.get_logger("gateway")

#: structured per-request access lines (diagnostics.access-log) go to
#: their own logger so operators can route/ship them separately
access_log = gflog.get_logger("gateway.access")

#: where the PUT-time content hash lives on the object (the reference
#: stores bit-rot signatures the same way: a trusted xattr beside the
#: data).  Plain ``user.`` namespace so fuse-side tooling can read it.
ETAG_XATTR = "user.gftpu.etag"

#: bodies up to this size are buffered and written as ONE compound
#: create+writev+fsetxattr+flush+release chain (a single round trip on
#: a compound-enabled volume); larger or chunked bodies stream through
#: write-behind windows instead
SMALL_BODY = 1 << 20

#: streamed uploads land under this name in the target's directory and
#: rename over the key on success — a torn body never replaces (or
#: destroys) the previous object version.  Filtered from listings.
TMP_PREFIX = ".gftpu.upload~"

#: GET bodies beyond this stream as bounded read windows instead of
#: one whole-object readv — a multi-GiB object (x a 512-client ladder)
#: must never materialize as single frames on brick and gateway
GET_STREAM_THRESHOLD = 8 << 20
GET_STREAM_WINDOW = 4 << 20

_READ_CHUNK = 256 << 10

_REASONS = {200: "OK", 204: "No Content", 206: "Partial Content",
            304: "Not Modified", 400: "Bad Request", 403: "Forbidden",
            404: "Not Found", 405: "Method Not Allowed",
            409: "Conflict", 411: "Length Required",
            416: "Range Not Satisfiable", 429: "Too Many Requests",
            500: "Internal Server Error", 503: "Service Unavailable",
            507: "Insufficient Storage"}

# one family set scraped over every live gateway instance (the
# register_objects weak-population pattern core/metrics documents)
_GATEWAYS = REGISTRY.register_objects(
    "gftpu_gateway_requests_total", "counter",
    "gateway HTTP requests by method and status",
    lambda gw: [({"method": m, "status": str(s)}, v)
                for (m, s), v in sorted(gw.requests.items())])
REGISTRY.register_objects(
    "gftpu_gateway_inflight", "gauge",
    "in-flight gateway HTTP requests", lambda gw: [({}, gw.inflight)],
    live=_GATEWAYS)
REGISTRY.register_objects(
    "gftpu_gateway_bytes_total", "counter",
    "gateway HTTP payload bytes by direction",
    lambda gw: [({"dir": "rx"}, gw.bytes_rx),
                ({"dir": "tx"}, gw.bytes_tx)], live=_GATEWAYS)
REGISTRY.register_objects(
    "gftpu_gateway_request_seconds", "gauge",
    "gateway request latency quantiles by method",
    lambda gw: [({"method": m, "quantile": q},
                 h.percentile(float(q)))
                for m, h in sorted(gw.latency.items()) if h.total
                for q in ("50", "99")], live=_GATEWAYS)
REGISTRY.register_objects(
    "gftpu_gateway_throttled_total", "counter",
    "connections refused past gateway.max-clients",
    lambda gw: [({}, gw.throttled)], live=_GATEWAYS)
REGISTRY.register_objects(
    "gftpu_gateway_body_writes_total", "counter",
    "GET bodies by socket-write shape (sg = multi-segment writelines, "
    "no join; joined = single-buffer write)",
    lambda gw: [({"shape": k}, v)
                for k, v in sorted(gw.body_writes.items())],
    live=_GATEWAYS)
REGISTRY.register_objects(
    "gftpu_gateway_events_total", "counter",
    "gateway lifecycle events emitted by kind",
    lambda gw: labeled(gw.events), live=_GATEWAYS)
REGISTRY.register_objects(
    "gftpu_gateway_pool", "gauge",
    "mounted glfs clients per gateway, and the reply-turning event "
    "workers (client.event-threads) those graphs share",
    lambda gw: [({"what": "clients"}, len(gw.pool.clients)),
                ({"what": "event_threads"}, gw.pool.event_threads())],
    live=_GATEWAYS)


class _HttpError(Exception):
    def __init__(self, status: int, message: str = "",
                 headers: dict | None = None):
        super().__init__(message or _REASONS.get(status, ""))
        self.status = status
        self.headers = headers or {}


class _Body:
    """One request's body stream, tracking whether it was consumed to
    the end — a response sent with body bytes still unread means the
    connection cannot be reused (the leftovers would be parsed as the
    next request: smuggling), so the serve loop checks ``consumed``
    after every dispatch."""

    def __init__(self, gw: "ObjectGateway", reader, headers: dict):
        self._gw = gw
        self._reader = reader
        self._headers = headers
        self._chunked = "chunked" in headers.get(
            "transfer-encoding", "").lower()
        self.consumed = not (self._chunked or
                             int(headers.get("content-length") or 0))

    async def chunks(self) -> AsyncIterator[bytes]:
        reader = self._reader
        if self._chunked:
            while True:
                line = await reader.readline()
                if not line:
                    # EOF before the terminal 0-chunk: a torn upload
                    # must NOT be committed as a complete object
                    raise ConnectionError("request body truncated")
                size = int(line.split(b";")[0].strip() or b"0", 16)
                if size == 0:
                    while True:  # drain trailers
                        t = await reader.readline()
                        if t in (b"\r\n", b"\n", b""):
                            break
                    self.consumed = True
                    return
                data = await reader.readexactly(size)
                await reader.readexactly(2)  # chunk CRLF
                self._gw.bytes_rx += len(data)
                yield data
        else:
            n = int(self._headers.get("content-length") or 0)
            while n > 0:
                chunk = await reader.read(min(n, _READ_CHUNK))
                if not chunk:
                    raise ConnectionError("request body truncated")
                n -= len(chunk)
                self._gw.bytes_rx += len(chunk)
                yield chunk
            self.consumed = True

    async def drain(self) -> None:
        async for _ in self.chunks():
            pass


_ERRNO_STATUS = {errno.ENOENT: 404, errno.ESTALE: 404,
                 errno.ENOTDIR: 404, errno.EISDIR: 400,
                 errno.EEXIST: 409, errno.ENOTEMPTY: 409,
                 errno.EACCES: 403, errno.EPERM: 403,
                 errno.EROFS: 403, errno.EDQUOT: 403,
                 errno.ENOSPC: 507,
                 errno.EINVAL: 400, errno.ENAMETOOLONG: 400}


def _status_of(e: FopError) -> int:
    return _ERRNO_STATUS.get(e.err, 500)


class ClientPool:
    """A fixed pool of mounted glfs clients handed out round-robin.

    One Client is one graph is a handful of TCP connections; pooling a
    few of them gives the gateway parallel wire pipelines without a
    graph per HTTP client (glfs_t is ~a mount, not ~a socket)."""

    def __init__(self, factory: Callable, size: int = 4):
        self._factory = factory  # async () -> mounted Client
        self.size = max(1, int(size))
        self.clients: list[Client] = []
        self._next = 0

    async def start(self) -> None:
        for _ in range(self.size):
            self.clients.append(await self._factory())

    def acquire(self) -> Client:
        c = self.clients[self._next % len(self.clients)]
        self._next += 1
        return c

    def event_threads(self) -> int:
        """Largest client.event-threads configured across the pooled
        graphs (they all share the process-wide reply-turning pool)."""
        from ..core.layer import walk
        from ..protocol.client import ClientLayer

        n = 0
        for c in self.clients:
            for layer in walk(c.graph.top):
                if isinstance(layer, ClientLayer):
                    n = max(n, int(layer.opts.get("event-threads", 0)))
        return n

    async def close(self) -> None:
        for c in self.clients:
            try:
                await c.unmount()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        self.clients.clear()


class _CacheEntry:
    __slots__ = ("gfid", "etag", "size", "mtime", "content")

    def __init__(self, gfid: bytes, etag: str, size: int, mtime,
                 content: bytes):
        self.gfid = gfid
        self.etag = etag
        self.size = size
        self.mtime = mtime
        self.content = content


class _ObjectCache:
    """LRU lease-held object cache (``gateway.object-cache-size``).

    Whole hot objects live here as owned bytes and are served — body,
    ETag, 304s, HEADs, ranges — with ZERO wire fops.  Coherence is the
    lease contract, not a TTL: an entry is only filled after
    ``lease_acquire`` succeeds on the filling pool client, and that
    client's held-lease registry gets :meth:`drop_gfid` as an
    ``on_drop`` callback — a recall (any conflicting writer, through
    any door) drops the entry *synchronously before the recall is
    acked*, so presence implies validity.  Local same-client writes
    never trigger a recall, so the gateway's own PUT/DELETE paths call
    :meth:`drop_path` directly."""

    CACHE_KIND = "gateway"  # the gftpu_cache_* {cache=...} label

    def __init__(self, limit: int):
        import collections

        self.limit = int(limit)
        self._m: "collections.OrderedDict[str, _CacheEntry]" = \
            collections.OrderedDict()
        self._by_gfid: dict[bytes, set[str]] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0
        self.recall_drops = 0
        cache_metrics.track(self)

    def get(self, path: str) -> _CacheEntry | None:
        ent = self._m.get(path)
        if ent is not None:
            self._m.move_to_end(path)
        return ent

    def put(self, path: str, ent: _CacheEntry) -> None:
        if ent.size > self.limit:
            return
        self.drop_path(path)
        self._m[path] = ent
        self._by_gfid.setdefault(ent.gfid, set()).add(path)
        self.bytes += ent.size
        while self.bytes > self.limit and self._m:
            old_path, old = self._m.popitem(last=False)
            self._unindex(old_path, old)

    def _unindex(self, path: str, ent: _CacheEntry) -> None:
        self.bytes -= ent.size
        paths = self._by_gfid.get(ent.gfid)
        if paths is not None:
            paths.discard(path)
            if not paths:
                del self._by_gfid[ent.gfid]

    def drop_path(self, path: str) -> None:
        ent = self._m.pop(path, None)
        if ent is not None:
            self._unindex(path, ent)

    def drop_gfid(self, gfid: bytes) -> None:
        """HeldLeases.on_drop hook — runs synchronously inside the
        recall's notify, before the release ack goes back."""
        for path in list(self._by_gfid.get(bytes(gfid), ())):
            self.recall_drops += 1
            self.drop_path(path)

    def dump(self) -> dict:
        return {"objects": len(self._m), "bytes": self.bytes,
                "limit": self.limit, "hits": self.hits,
                "misses": self.misses,
                "recall_drops": self.recall_drops}


class ObjectGateway:
    """The HTTP front door (one instance per served volume)."""

    def __init__(self, pool: ClientPool, host: str = "127.0.0.1",
                 port: int = 0, max_clients: int = 512,
                 volume: str = "", object_cache_size: int = 0,
                 qos_fops: float = 0.0, qos_bytes: float = 0.0,
                 qos_burst: float = 1.0):
        self.pool = pool
        self.host = host
        self.port = port
        self.max_clients = int(max_clients)
        self.volume = volume
        self._server: asyncio.AbstractServer | None = None
        self.conns = 0
        self.inflight = 0
        self.requests: dict[tuple[str, int], int] = {}
        self.latency: dict[str, LogHistogram] = {}
        self.bytes_rx = 0
        self.bytes_tx = 0
        self.throttled = 0
        self.body_writes = {"sg": 0, "joined": 0}
        self.sg_segments = 0  # segments written without a join, total
        self.events = {"GATEWAY_START": 0, "GATEWAY_STOP": 0,
                       "GATEWAY_CLIENT_THROTTLED": 0}
        self._tmp_seq = itertools.count()
        # lease-held whole-object cache (0 = off); with workers=N each
        # worker process builds its own, kept coherent by its own pool
        # clients' upcall sinks
        self._ocache = _ObjectCache(object_cache_size) \
            if int(object_cache_size) > 0 else None
        # gfid-keyed ETag memo validated by (mtime, size) — conditional
        # GETs/HEADs skip the per-request wire getxattr.  Every gateway
        # PUT commits to a FRESH gfid (O_EXCL create or temp+rename),
        # so a stale memo entry can never match the new object's stat
        import collections

        self._etags: "collections.OrderedDict[bytes, tuple]" = \
            collections.OrderedDict()
        self.etag_fast_hits = 0
        # gfids whose STORED etag xattr can no longer be trusted: an
        # out-of-band writer (fuse/glfs, another door) modified the
        # object in place, which invalidates both the memo AND the
        # persisted hash.  Fed by the pool clients' upcall
        # invalidations (Client.on_invalidate); the value is a
        # generation counter so every overwrite changes the weak
        # validator _etag_of synthesizes for a dirty gfid
        self._etag_dirty: dict[bytes, int] = {}
        self.etag_invalidations = 0
        # per-HTTP-peer QoS buckets (features/qos, door="gateway"):
        # HTTP clients inherit the same admission model the brick
        # applies per connection identity, keyed by peer IP so a
        # greedy curl loop is shaped no matter how many connections
        # it opens.  Sheds answer 429 + Retry-After — the HTTP
        # spelling of the brick's EAGAIN + qos-throttle notice.
        self._qos = None
        if float(qos_fops) > 0 or float(qos_bytes) > 0:
            from ..features.qos import QosEngine

            self._qos_opts = {"qos": "on",
                              "qos-fops-per-sec": float(qos_fops),
                              "qos-bytes-per-sec": float(qos_bytes),
                              "qos-burst": float(qos_burst)}
            self._qos = QosEngine(volume or "gateway",
                                  lambda: self._qos_opts,
                                  door="gateway")
        _GATEWAYS.add(self)

    # -- lifecycle ---------------------------------------------------------

    def _event(self, kind: str, **fields) -> None:
        self.events[kind] = self.events.get(kind, 0) + 1
        gf_events.gf_event(kind, volume=self.volume, port=self.port,
                           **fields)

    async def start(self, sock=None, listen: bool = True) -> None:
        """``sock``: serve an already-bound listening socket (the
        SO_REUSEPORT worker-pool lane — each worker binds its own).
        ``listen=False``: no listener at all — connections arrive as
        passed fds (the SCM_RIGHTS fallback lane) and the owner feeds
        them to :meth:`_serve_conn` directly."""
        if not self.pool.clients:
            await self.pool.start()
        if self._ocache is not None:
            # recall-exact coherence: any pool client losing a lease
            # (recall, expiry, disconnect) drops the object's cache
            # entries synchronously, before the recall is acked
            for c in self.pool.clients:
                if self._ocache.drop_gfid not in c.leases.on_drop:
                    c.leases.on_drop.append(self._ocache.drop_gfid)
        # ETag-memo coherence for OUT-OF-BAND writers: an upcall
        # invalidation against any pool client marks the gfid dirty,
        # so a fuse-side in-place overwrite can't keep serving the
        # pre-overwrite hash to conditional GETs (the stored xattr is
        # stale too — _etag_of switches to a weak validator)
        for c in self.pool.clients:
            if self._etag_invalidate not in c.on_invalidate:
                c.on_invalidate.append(self._etag_invalidate)
        # pool-aware event plane: pre-size the shared reply-turning
        # workers to the pooled graphs' client.event-threads so the
        # first heavy GET doesn't pay the pool spin-up
        from ..rpc import event_pool as _evt

        _evt.client_pool(self.pool.event_threads())
        if sock is not None:
            self._server = await asyncio.start_server(
                self._serve_conn, sock=sock)
            self.port = self._server.sockets[0].getsockname()[1]
        elif listen:
            self._server = await asyncio.start_server(
                self._serve_conn, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]
        # the incident bundle's gateway section: this door's request /
        # cache / pool accounting rides every snapshot
        flight.add_section("gateway", self.dump)
        self._event("GATEWAY_START", pool=self.pool.size,
                    max_clients=self.max_clients)
        log.info(2, "object gateway for %s on %s:%d (pool=%d)",
                 self.volume or "<volfile>", self.host, self.port,
                 self.pool.size)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        await self.pool.close()
        self._event("GATEWAY_STOP")

    # -- HTTP plumbing -----------------------------------------------------

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        if self.conns >= self.max_clients:
            # admission control: shed the CONNECTION before parsing
            # anything (a saturated gateway must stay cheap to refuse)
            self.throttled += 1
            self._event("GATEWAY_CLIENT_THROTTLED",
                        conns=self.conns, limit=self.max_clients)
            try:
                # even a shed connection gets a trace id: the 503 body
                # names it so a client report can be joined to this
                # process's flight ring
                tid = tracing.new_trace_id() if tracing.ENABLED else ""
                body = json.dumps({"error": "gateway saturated",
                                   "trace": tid}).encode()
                writer.write(b"HTTP/1.1 503 Service Unavailable\r\n"
                             b"Connection: close\r\n"
                             b"Retry-After: 1\r\n"
                             b"Content-Type: application/json\r\n" +
                             (f"X-Gftpu-Trace: {tid}\r\n".encode()
                              if tid else b"") +
                             b"Content-Length: " +
                             str(len(body)).encode() + b"\r\n\r\n" +
                             body)
                await writer.drain()
            except ConnectionError:
                pass
            finally:
                writer.close()
            return
        self.conns += 1
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except (asyncio.IncompleteReadError,
                        asyncio.LimitOverrunError, ConnectionError,
                        ValueError):
                    break
                if req is None:
                    break
                method, target, headers = req
                cl = headers.get("content-length")
                if cl is not None and not cl.strip().isdigit():
                    # malformed framing header: 400 and drop the
                    # connection (the body length is unknowable)
                    await self._respond(
                        writer, 400,
                        {"content-type": "application/json"},
                        b'{"error": "bad Content-Length"}')
                    break
                body = _Body(self, reader, headers)
                keep = headers.get("connection", "").lower() != "close"
                # stats key off a closed vocabulary: arbitrary client
                # method strings must not grow the label sets unbounded
                mkey = method if method in (
                    "GET", "PUT", "HEAD", "DELETE", "POST",
                    "OPTIONS") else "OTHER"
                self.inflight += 1
                # ONE trace id per HTTP request, minted HERE and armed
                # on this task's context: every pooled-glfs fop below
                # inherits it, protocol/client ships it on the wire,
                # and the brick re-arms it — a merged incident bundle
                # shows this GET's waterfall across gateway worker →
                # client graph → N brick daemons.  _respond reads it
                # back as the X-Gftpu-Trace response header.
                tid = ""
                if tracing.ENABLED:
                    tid = tracing.new_trace_id()
                    tracing.arm(tid)
                t0 = time.perf_counter()
                tx0 = self.bytes_tx
                status = 500
                try:
                    status = await self._dispatch(
                        method, target, headers, body, writer)
                except ConnectionError:
                    break
                finally:
                    self.inflight -= 1
                    if self._qos is not None:
                        # reply bytes borrow against the peer's bytes
                        # bucket (the brick's post-send charge): a big
                        # GET delays the NEXT admission, never its own
                        self._qos.charge(self._qos_ident(writer),
                                         self.bytes_tx - tx0)
                    self.requests[(mkey, status)] = \
                        self.requests.get((mkey, status), 0) + 1
                    ms = (time.perf_counter() - t0) * 1e3
                    self.latency.setdefault(
                        mkey, LogHistogram()).record(ms / 1e3)
                    if flight.ACCESS_LOG:
                        # diagnostics.access-log: one structured line
                        # per request — grep-able AND json-parseable
                        access_log.info(
                            9, "%s", json.dumps(
                                {"method": method, "path": target,
                                 "status": status,
                                 "bytes": self.bytes_tx - tx0,
                                 "ms": round(ms, 3), "trace": tid},
                                sort_keys=True))
                    if status >= 500:
                        flight.record(
                            "gateway_5xx", method=method, path=target,
                            status=status, trace=tid,
                            ms=round(ms, 3))
                if not body.consumed:
                    # a response went out before the request body was
                    # fully read (error mid-PUT): the leftover body
                    # bytes MUST NOT be parsed as the next request
                    # (request smuggling) — drop the connection
                    break
                if not keep:
                    break
        finally:
            self.conns -= 1
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    async def _read_request(reader):
        line = await reader.readline()
        if not line:
            return None
        parts = line.decode("latin-1").rstrip("\r\n").split()
        if len(parts) != 3 or not parts[2].startswith("HTTP/"):
            raise ValueError("malformed request line")
        headers: dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode("latin-1").partition(":")
            headers[k.strip().lower()] = v.strip()
        return parts[0].upper(), parts[1], headers

    async def _respond(self, writer, status: int,
                       headers: dict[str, Any] | None = None,
                       body=None, head: bool = False) -> int:
        hdrs = dict(headers or {})
        tid = tracing.current_id() if tracing.ENABLED else None
        if tid:
            # the request's trace id goes back to the caller: quote it
            # in a support report and `volume incident show` finds the
            # exact cross-process waterfall
            hdrs.setdefault("X-Gftpu-Trace", tid)
        if body is None:
            length = int(hdrs.pop("content-length", 0))
        else:
            length = len(body)
        head_lines = [f"HTTP/1.1 {status} "
                      f"{_REASONS.get(status, 'OK')}",
                      f"Content-Length: {length}"]
        head_lines += [f"{k}: {v}" for k, v in hdrs.items()]
        prefix = ("\r\n".join(head_lines) + "\r\n\r\n").encode("latin-1")
        if head or body is None or length == 0:
            writer.write(prefix)
        elif isinstance(body, SGBuf) and len(body.segments) > 1:
            # the zero-copy lane: response head + every payload segment
            # in ONE gathered writelines — the segments are wire-frame /
            # page-cache views that were never joined
            writer.writelines([prefix, *body.segments])
            self.body_writes["sg"] += 1
            self.sg_segments += len(body.segments)
        else:
            if isinstance(body, SGBuf):
                body = body.segments[0] if body.segments else b""
            writer.writelines([prefix, body])
            self.body_writes["joined"] += 1
        if not head and body is not None:
            self.bytes_tx += length
        await writer.drain()
        return status

    # -- request routing ---------------------------------------------------

    @staticmethod
    def _split_target(target: str) -> tuple[list[str], dict]:
        path, _, query = target.partition("?")
        comps = [urllib.parse.unquote(c)
                 for c in path.split("/") if c != ""]
        for c in comps:
            # validated AFTER unquoting: a %2F inside a component
            # would otherwise smuggle '..' segments past this check
            # and normpath would walk them out of the bucket
            if c in (".", "..") or "/" in c or "\x00" in c:
                raise _HttpError(400, "bad path component")
        q = urllib.parse.parse_qs(query, keep_blank_values=True)
        return comps, {k: v[-1] for k, v in q.items()}

    @staticmethod
    def _qos_ident(writer) -> str:
        """QoS identity of an HTTP request: the peer IP — buckets span
        connections, so a greedy client can't dodge shaping by opening
        more sockets (fd-passed / unix peers pool under 'local')."""
        peer = writer.get_extra_info("peername")
        if isinstance(peer, (tuple, list)) and peer:
            return str(peer[0])
        return "local"

    async def _qos_gate(self, method: str, comps: list, headers,
                        writer) -> None:
        """Per-request admission against the peer's bucket pair.
        A lease-held object-cache hit skips the fops bucket entirely:
        it is served at ZERO wire fops, the cheapest possible citizen,
        and shaping it could pressure the lease plane (QoS never
        recalls a lease just to shape).  Reply bytes are still charged
        after the response via the tx delta in _serve_conn."""
        if method in ("GET", "HEAD") and len(comps) >= 2 and \
                self._ocache is not None and self._ocache.get(
                    f"/{comps[0]}/{'/'.join(comps[1:])}") is not None:
            return
        verdict, wait_s, why = self._qos.admit(
            self._qos_ident(writer), fop=method.lower(),
            nbytes=int(headers.get("content-length") or 0))
        if verdict == "shed":
            # the HTTP spelling of the brick's EAGAIN + notice; the
            # request body is left unread, so the serve loop drops the
            # connection after the response (correct: reading a shed
            # PUT's body would do the work QoS just refused)
            raise _HttpError(429, f"qos throttled ({why})",
                             {"retry-after": max(1, int(wait_s + 1))})
        if verdict == "shape":
            await asyncio.sleep(wait_s)

    async def _dispatch(self, method, target, headers, body,
                        writer) -> int:
        try:
            comps, query = self._split_target(target)
            if self._qos is not None:
                await self._qos_gate(method, comps, headers, writer)
            c = self.pool.acquire()
            if not comps:
                if method in ("GET", "HEAD"):
                    await body.drain()
                    return await self._list_buckets(
                        c, writer, head=method == "HEAD")
                raise _HttpError(405)
            if len(comps) == 1:
                return await self._bucket_op(c, method, comps[0],
                                             query, headers, body,
                                             writer)
            bucket, key = comps[0], "/".join(comps[1:])
            if method == "PUT":
                return await self._put_object(c, bucket, key, headers,
                                              body, writer)
            await body.drain()
            if method in ("GET", "HEAD"):
                return await self._get_object(
                    c, bucket, key, headers, writer,
                    head=method == "HEAD")
            if method == "DELETE":
                await c.unlink(f"/{bucket}/{key}")
                if self._ocache is not None:
                    # same-client deletes don't recall our own lease —
                    # drop the entry ourselves, synchronously
                    self._ocache.drop_path(f"/{bucket}/{key}")
                return await self._respond(writer, 204)
            raise _HttpError(405)
        except _HttpError as e:
            # 5xx and the admission-throttle 429 carry the trace id in
            # the body too: a client that logs only bodies still gets
            # the handle into the flight ring
            err = {"error": str(e) or _REASONS.get(e.status, "")}
            if e.status in (429, 503) or e.status >= 500:
                err["trace"] = tracing.current_id() or ""
            body = json.dumps(err).encode()
            return await self._respond(
                writer, e.status,
                {"content-type": "application/json", **e.headers},
                b"" if e.status == 304 else body,
                head=method == "HEAD")
        except FopError as e:
            status = _status_of(e)
            err = {"error": str(e), "errno": e.err}
            if status >= 500:
                err["trace"] = tracing.current_id() or ""
            body = json.dumps(err).encode()
            return await self._respond(
                writer, status, {"content-type": "application/json"},
                body, head=method == "HEAD")
        except (asyncio.IncompleteReadError, ConnectionError):
            raise ConnectionError
        except Exception as e:  # noqa: BLE001 - one request, not the daemon
            log.error(3, "gateway request failed: %r", e)
            return await self._respond(
                writer, 500, {"content-type": "application/json"},
                json.dumps({"error": repr(e),
                            "trace": tracing.current_id() or ""}
                           ).encode(),
                head=method == "HEAD")

    # -- buckets -----------------------------------------------------------

    async def _list_buckets(self, c: Client, writer,
                            head: bool = False) -> int:
        out = []
        for name, ia in sorted(await c.listdir_with_stat("/")):
            if ia is not None and ia.is_dir():
                out.append({"name": name,
                            "created": getattr(ia, "ctime", 0)})
        body = json.dumps({"buckets": out}).encode()
        return await self._respond(
            writer, 200, {"content-type": "application/json"}, body,
            head=head)

    async def _bucket_op(self, c: Client, method: str, bucket: str,
                         query: dict, headers, body, writer) -> int:
        if method == "PUT":
            await body.drain()
            try:
                await c.mkdir(f"/{bucket}")
            except FopError as e:
                if e.err != errno.EEXIST:  # idempotent create (S3: 200)
                    raise
            return await self._respond(writer, 200)
        await body.drain()
        if method == "DELETE":
            await c.rmdir(f"/{bucket}")
            return await self._respond(writer, 204)
        if method == "HEAD":
            ia = await c.stat(f"/{bucket}")
            if not ia.is_dir():
                raise _HttpError(404, "not a bucket")
            return await self._respond(writer, 200, head=True)
        if method == "GET":
            return await self._list_objects(c, bucket, query, writer)
        raise _HttpError(405)

    # -- listing -----------------------------------------------------------

    async def _walk_keys(self, c: Client, root: str, rel: str,
                         out: list) -> None:
        for name, ia in sorted(await c.listdir_with_stat(root)):
            if name.startswith(TMP_PREFIX):
                continue  # in-flight uploads are not objects
            child = f"{root.rstrip('/')}/{name}"
            key = f"{rel}{name}"
            if ia is not None and ia.is_dir():
                await self._walk_keys(c, child, key + "/", out)
            else:
                out.append((key, ia))

    async def _list_objects(self, c: Client, bucket: str, query: dict,
                            writer) -> int:
        ia = await c.stat(f"/{bucket}")  # 404 on missing bucket
        if not ia.is_dir():
            raise _HttpError(404, "not a bucket")
        prefix = query.get("prefix", "")
        # prefix flows into brick paths: the same traversal rules as
        # path components, or '../other-bucket/' escapes the scope
        if any(p in (".", "..") or "\x00" in p
               for p in prefix.split("/")):
            raise _HttpError(400, "bad prefix")
        marker = query.get("marker", "")
        delim = query.get("delimiter", "")
        try:
            max_keys = min(int(query.get("max-keys", 1000)), 100000)
        except ValueError:
            raise _HttpError(400, "bad max-keys")
        walked: list = []
        # delimiter='/' + a directory-shaped prefix is ONE readdir on
        # the prefix directory (the nested-dir key mapping exists for
        # exactly this); anything else pays the recursive walk
        if delim == "/" and (prefix == "" or prefix.endswith("/")):
            base = f"/{bucket}/{prefix}".rstrip("/") or f"/{bucket}"
            try:
                for name, e_ia in sorted(await c.listdir_with_stat(base)):
                    if name.startswith(TMP_PREFIX):
                        continue  # in-flight uploads are not objects
                    if e_ia is not None and e_ia.is_dir():
                        walked.append((f"{prefix}{name}/", None))
                    else:
                        walked.append((f"{prefix}{name}", e_ia))
            except FopError as e:
                if e.err not in (errno.ENOENT, errno.ESTALE,
                                 errno.ENOTDIR):
                    raise  # empty prefix dir -> empty listing
        else:
            # root the recursive walk at the prefix's directory
            # component: O(matching subtree) round trips, not
            # O(bucket) (a missing subtree is just an empty listing).
            # KNOWN COST: each PAGE of a paged listing re-walks the
            # subtree (marker/max-keys apply after the sorted walk —
            # the unsorted depth-first order can't early-exit
            # correctly); true incremental paging needs readdir-offset
            # cursors, an open follow-up
            pdir, _, _rest = prefix.rpartition("/")
            root = f"/{bucket}/{pdir}" if pdir else f"/{bucket}"
            try:
                await self._walk_keys(c, root,
                                      f"{pdir}/" if pdir else "",
                                      walked)
            except FopError as e:
                if e.err not in (errno.ENOENT, errno.ESTALE,
                                 errno.ENOTDIR):
                    raise
            walked = [(k, e) for k, e in walked if k.startswith(prefix)]
            if delim:
                grouped: list = []
                seen: set[str] = set()
                for k, e in walked:
                    rest = k[len(prefix):]
                    if delim in rest:
                        cp = prefix + rest.split(delim)[0] + delim
                        if cp not in seen:
                            seen.add(cp)
                            grouped.append((cp, None))
                    else:
                        grouped.append((k, e))
                walked = grouped
        walked.sort(key=lambda t: t[0])
        keys, prefixes = [], []
        truncated = False
        next_marker = ""
        # max-keys <= 0 is an empty NON-truncated page (S3 shape): a
        # truncated=true answer with an empty next_marker would send
        # paging clients into an infinite identical-request loop
        for k, e in walked if max_keys > 0 else ():
            if marker and k <= marker:
                continue
            if len(keys) + len(prefixes) >= max_keys:
                truncated = True
                break
            next_marker = k
            if e is None and (delim and k.endswith(delim)):
                prefixes.append(k)
            else:
                keys.append({"key": k,
                             "size": getattr(e, "size", 0),
                             "mtime": getattr(e, "mtime", 0)})
        body = json.dumps({
            "bucket": bucket, "prefix": prefix, "marker": marker,
            "delimiter": delim, "max_keys": max_keys, "keys": keys,
            "common_prefixes": prefixes, "truncated": truncated,
            "next_marker": next_marker if truncated else ""}).encode()
        return await self._respond(
            writer, 200, {"content-type": "application/json"}, body)

    # -- objects -----------------------------------------------------------

    async def _ensure_parents(self, c: Client, bucket: str,
                              key: str) -> None:
        """Create the key's intermediate directories — but never the
        bucket itself: an ENOENT at the first component means the
        bucket is missing, which is the caller's 404, not an implicit
        bucket create."""
        parts = key.split("/")[:-1]
        if not parts:
            if not await c.exists(f"/{bucket}"):
                raise _HttpError(404, f"no such bucket {bucket!r}")
            return
        cur = f"/{bucket}"
        for i, p in enumerate(parts):
            cur = f"{cur}/{p}"
            try:
                await c.mkdir(cur)
            except FopError as e:
                if e.err in (errno.ENOENT, errno.ESTALE) and i == 0:
                    raise _HttpError(404,
                                     f"no such bucket {bucket!r}")
                if e.err != errno.EEXIST:
                    raise

    async def _put_object(self, c: Client, bucket: str, key: str,
                          headers, body, writer) -> int:
        if "content-length" not in headers and \
                "chunked" not in headers.get("transfer-encoding",
                                             "").lower():
            raise _HttpError(411)
        # no up-front bucket probe: the create's own ENOENT tells a
        # missing bucket apart (via _ensure_parents), so the hot PUT
        # path pays zero extra round trips
        length = headers.get("content-length")
        chunks = body.chunks()
        if length is not None and int(length) <= SMALL_BODY:
            buf = bytearray()
            async for chunk in chunks:
                buf += chunk
            etag = await self._write_small(c, bucket, key, bytes(buf))
        else:
            etag = await self._write_stream(c, bucket, key, chunks)
        if self._ocache is not None:
            # a PUT through our own pool client doesn't recall our own
            # lease (same client identity) — drop synchronously so the
            # next GET refills from the new object
            self._ocache.drop_path(f"/{bucket}/{key}")
        return await self._respond(writer, 200,
                                   {"etag": f'"{etag}"'}, b"")

    async def _write_small(self, c: Client, bucket: str, key: str,
                           body: bytes) -> str:
        """Whole small object in one pass; on a compound volume the
        fresh-object case is ONE chain — create+writev+fsetxattr+flush+
        release in a single round trip where the graph carries it (the
        write_file chain plus the ETag xattr riding the same frame).
        An EXISTING object (or a non-compound graph) goes through the
        temp+rename commit so an overwrite is atomic."""
        path = f"/{bucket}/{key}"
        etag = hashlib.sha256(body).hexdigest()
        xattrs = {ETAG_XATTR: etag.encode()}
        if c._use_compound():
            from ..rpc import compound as cfop

            for attempt in (0, 1):
                try:
                    loc = await c._parent_loc(path)
                except FopError as e:
                    if e.err in (errno.ENOENT, errno.ESTALE) \
                            and attempt == 0:
                        await self._ensure_parents(c, bucket, key)
                        continue
                    raise
                replies = await c.graph.top.compound([
                    ("create", (loc, os.O_RDWR | os.O_EXCL, 0o644), {}),
                    ("writev", (cfop.FdRef(0), body, 0), {}),
                    ("fsetxattr", (cfop.FdRef(0), xattrs, 0), {}),
                    ("flush", (cfop.FdRef(0),), {}),
                    ("release", (cfop.FdRef(0),), {})])
                err = cfop.first_error(replies)
                if err is None:
                    created = replies[0][1]
                    ia = created[1] if isinstance(
                        created, (list, tuple)) and len(created) > 1 \
                        else None
                    if hasattr(ia, "gfid"):
                        c.itable.link(loc.parent, loc.name, ia.gfid,
                                      ia.ia_type, ia)
                    return etag
                if err.err == errno.EEXIST:
                    break  # overwrite: temp+rename path below
                if replies and replies[0][0] == "ok":
                    # the chain created the object but a LATER link
                    # failed (ENOSPC mid-writev, ESTALE mid-chain...):
                    # chains skip, they don't roll back — remove the
                    # partial fresh object BEFORE any retry, so a
                    # failed PUT commits nothing and a retry's create
                    # doesn't trip over attempt 0's debris (the create
                    # was O_EXCL, so no previous version existed here)
                    try:
                        await c.unlink(path)
                    except FopError:
                        pass
                if err.err in (errno.ENOENT, errno.ESTALE) \
                        and attempt == 0:
                    await self._ensure_parents(c, bucket, key)
                    continue
                raise err

        async def once():
            yield body

        return await self._write_stream(c, bucket, key, once())

    async def _create_temp(self, c: Client, bucket: str, key: str):
        """Create the upload's temp file in the target's directory
        (rename stays within one dht subvolume placement step)."""
        head, _, base = key.rpartition("/")
        tmp_key = (f"{head}/" if head else "") + \
            f"{TMP_PREFIX}{base}.{os.getpid()}.{next(self._tmp_seq)}"
        path = f"/{bucket}/{tmp_key}"
        for attempt in (0, 1):
            try:
                return tmp_key, await c.create(path,
                                               os.O_RDWR | os.O_EXCL)
            except FopError as e:
                if e.err in (errno.ENOENT, errno.ESTALE) \
                        and attempt == 0:
                    await self._ensure_parents(c, bucket, key)
                    continue
                raise

    async def _write_stream(self, c: Client, bucket: str, key: str,
                            chunks) -> str:
        """Multipart-style streaming PUT: request-body chunks land as
        sequential writes that write-behind aggregates into window
        flush chains (+flush rides the drain frame at close) — the
        round-trip count is pinned by tests/test_gateway.py.  The
        stream commits via temp + rename, so a torn body neither
        replaces nor destroys the previous object version."""
        tmp_key, f = await self._create_temp(c, bucket, key)
        tmp = f"/{bucket}/{tmp_key}"
        h = hashlib.sha256()
        offset = 0
        try:
            async for chunk in chunks:
                h.update(chunk)
                await f.write(bytes(chunk), offset)
                offset += len(chunk)
            etag = h.hexdigest()
            await f.fsetxattr({ETAG_XATTR: etag.encode()})
            await f.close()
            await c.rename(tmp, f"/{bucket}/{key}")
        except BaseException:
            # torn body / failed commit: remove the temp, the previous
            # object version (if any) is untouched
            try:
                await f.close()
            finally:
                try:
                    await c.unlink(tmp)
                except FopError:
                    pass
            raise
        return etag

    @staticmethod
    def _parse_range(spec: str, size: int) -> tuple[int, int] | None:
        """``bytes=a-b`` -> (offset, length); None = whole body.
        Raises 416 for a start past EOF (RFC 9110 semantics)."""
        if not spec or not spec.startswith("bytes="):
            return None
        r = spec[len("bytes="):].split(",")[0].strip()  # first range
        start_s, _, end_s = r.partition("-")
        try:
            if start_s == "":  # suffix form: last N bytes
                n = int(end_s)
                if n <= 0:
                    raise ValueError
                start = max(0, size - n)
                end = size - 1
            else:
                start = int(start_s)
                end = int(end_s) if end_s else size - 1
        except ValueError:
            raise _HttpError(400, f"bad Range {spec!r}")
        if start >= size or start > end:
            raise _HttpError(416, "range past EOF",
                             {"content-range": f"bytes */{size}"})
        end = min(end, size - 1)
        return start, end - start + 1

    _ETAG_MEMO_MAX = 4096

    def _etag_invalidate(self, gfid: bytes) -> None:
        """Client.on_invalidate tap (upcall plane): another client
        wrote this gfid through another door.  Drop the memo entry AND
        remember the gfid as dirty — unlike a gateway PUT (which
        always commits to a fresh gfid), an in-place overwrite leaves
        the persisted ETag xattr describing the OLD bytes, so re-read
        validation isn't enough; _etag_of must stop trusting it."""
        gfid = bytes(gfid)
        self.etag_invalidations += 1
        self._etags.pop(gfid, None)
        self._etag_dirty[gfid] = self._etag_dirty.get(gfid, 0) + 1
        while len(self._etag_dirty) > self._ETAG_MEMO_MAX:
            self._etag_dirty.pop(next(iter(self._etag_dirty)))

    async def _etag_of(self, c: Client, path: str, ia=None) -> str:
        # the conditional-GET fast path: a memo entry whose (mtime,
        # size) still matches the stat we already paid skips the wire
        # getxattr every 304/HEAD used to cost
        gfid = bytes(ia.gfid) if ia is not None and \
            getattr(ia, "gfid", None) else None
        if gfid is not None:
            gen = self._etag_dirty.get(gfid)
            if gen is not None:
                # out-of-band overwrite: both the memo and the stored
                # xattr hash may describe the pre-overwrite bytes.
                # Serve a weak validator derived from what the stat in
                # hand proves about the CURRENT bytes (+ the upcall
                # generation, so even a same-second same-size
                # overwrite changes the tag)
                return (f"W-{int(ia.mtime * 1e9):x}"
                        f"-{ia.size:x}-{gen:x}")
            memo = self._etags.get(gfid)
            if memo is not None and memo[0] == ia.mtime and \
                    memo[1] == ia.size:
                self._etags.move_to_end(gfid)
                self.etag_fast_hits += 1
                return memo[2]
        try:
            out = await c.getxattr(path, ETAG_XATTR)
            val = out.get(ETAG_XATTR) if isinstance(out, dict) else out
            if val:
                etag = bytes(val).decode("latin-1")
                if gfid is not None:
                    self._etags[gfid] = (ia.mtime, ia.size, etag)
                    while len(self._etags) > self._ETAG_MEMO_MAX:
                        self._etags.popitem(last=False)
                return etag
        except FopError:
            pass  # written outside the gateway: no stored hash
        return ""

    async def _stream_body(self, writer, c: Client, path: str,
                           offset: int, total: int, status: int,
                           headers: dict) -> int:
        """Large GET bodies: open ONCE, then bounded raw readv windows
        on the held fd straight to the socket — segments stay unjoined
        per window, nothing ever holds the whole object, and the held
        fd keeps the streamed object stable against a concurrent
        replace.  Once the head is out, ANY failure tears the
        connection down: a second response injected mid-body would
        desync every later request on the connection."""
        f = await c.open(path, os.O_RDONLY)  # pre-head errors -> 4xx
        try:
            head_lines = [f"HTTP/1.1 {status} "
                          f"{_REASONS.get(status, 'OK')}",
                          f"Content-Length: {total}"]
            head_lines += [f"{k}: {v}" for k, v in headers.items()]
            writer.write(("\r\n".join(head_lines)
                          + "\r\n\r\n").encode("latin-1"))
            pos = 0
            try:
                while pos < total:
                    data = await c.graph.top.readv(
                        f.fd, min(GET_STREAM_WINDOW, total - pos),
                        offset + pos)
                    n = len(data)
                    if not n:
                        break  # short object: handled below
                    if isinstance(data, SGBuf) and \
                            len(data.segments) > 1:
                        writer.writelines(data.segments)
                        self.body_writes["sg"] += 1
                        self.sg_segments += len(data.segments)
                    else:
                        if isinstance(data, SGBuf):
                            data = data.segments[0] \
                                if data.segments else b""
                        writer.write(data)
                        self.body_writes["joined"] += 1
                    await writer.drain()
                    pos += n
            except ConnectionError:
                raise
            except Exception as e:  # noqa: BLE001 - head already sent
                raise ConnectionError(
                    f"mid-stream failure: {e!r}") from e
            self.bytes_tx += pos
            if pos != total:
                # the object shrank mid-stream: the framed length is
                # now a lie and the connection cannot be reused
                raise ConnectionError("object shrank mid-GET")
        finally:
            try:
                await f.close()
            except FopError:
                pass
        return status

    async def _serve_cached(self, ent: _CacheEntry, headers, writer,
                            head: bool) -> int:
        """Serve a GET/HEAD/304/range entirely from a lease-held cache
        entry — ZERO wire fops.  Presence implies validity: a recall
        drops the entry synchronously before it is acked, so nothing
        stale can be sitting here."""
        self._ocache.hits += 1
        inm = headers.get("if-none-match", "").strip('"')
        if ent.etag and inm and inm == ent.etag:
            raise _HttpError(304, headers={"etag": f'"{ent.etag}"'})
        base_headers: dict[str, Any] = {
            "content-type": "application/octet-stream",
            "accept-ranges": "bytes",
            "last-modified": str(ent.mtime),
            "etag": f'"{ent.etag}"'}
        if head:
            base_headers["content-length"] = ent.size
            return await self._respond(writer, 200, base_headers,
                                       head=True)
        rng = self._parse_range(headers.get("range", ""), ent.size)
        if rng is not None:
            offset, want = rng
            base_headers["content-range"] = \
                f"bytes {offset}-{offset + want - 1}/{ent.size}"
            self._ocache.hit_bytes += want
            return await self._respond(
                writer, 206, base_headers,
                SGBuf([memoryview(ent.content)[offset:offset + want]]))
        self._ocache.hit_bytes += ent.size
        return await self._respond(
            writer, 200, base_headers,
            SGBuf([ent.content]) if ent.size else b"")

    async def _fill_cache(self, c: Client, path: str, ia, etag: str,
                          data) -> None:
        """Admit a just-served whole object — but only under a lease
        (no lease, no zero-RT contract, no entry).  The one join this
        pays is the price of owning the bytes past the request."""
        if not getattr(ia, "gfid", None):
            return
        if not await c.lease_acquire(path):
            return
        if c.leases.get(bytes(ia.gfid)) is None:
            return  # the path re-resolved to a different gfid
        content = bytes(as_single_buffer(data))
        self._ocache.put(path, _CacheEntry(
            bytes(ia.gfid), etag, len(content),
            getattr(ia, "mtime", 0), content))

    async def _get_object(self, c: Client, bucket: str, key: str,
                          headers, writer, head: bool = False) -> int:
        path = f"/{bucket}/{key}"
        if self._ocache is not None:
            ent = self._ocache.get(path)
            if ent is not None:
                return await self._serve_cached(ent, headers, writer,
                                                head)
            self._ocache.misses += 1
        ia = await c.stat(path)
        if ia.is_dir():
            raise _HttpError(404, "key is a directory")
        etag = await self._etag_of(c, path, ia)
        inm = headers.get("if-none-match", "").strip('"')
        if etag and inm and inm == etag:
            raise _HttpError(304, headers={"etag": f'"{etag}"'})
        base_headers: dict[str, Any] = {
            "content-type": "application/octet-stream",
            "accept-ranges": "bytes",
            "last-modified": str(getattr(ia, "mtime", 0))}
        if etag:
            base_headers["etag"] = f'"{etag}"'
        rng = self._parse_range(headers.get("range", ""), ia.size)
        if head:
            base_headers["content-length"] = ia.size
            return await self._respond(writer, 200, base_headers,
                                       head=True)
        if rng is not None:
            offset, want = rng
            if want > GET_STREAM_THRESHOLD:
                base_headers["content-range"] = \
                    f"bytes {offset}-{offset + want - 1}/{ia.size}"
                return await self._stream_body(writer, c, path,
                                               offset, want, 206,
                                               base_headers)
            # the raw ranged window: SGBuf wire/page segments, no join
            data = await c.read_file(path, offset=offset, size=want)
            base_headers["content-range"] = \
                f"bytes {offset}-{offset + len(data) - 1}/{ia.size}"
            return await self._respond(writer, 206, base_headers, data)
        if ia.size == 0:
            return await self._respond(writer, 200, base_headers, b"")
        if ia.size > GET_STREAM_THRESHOLD:
            return await self._stream_body(writer, c, path, 0,
                                           ia.size, 200, base_headers)
        data = await c.read_file(path, offset=0, size=ia.size)
        if not etag:
            # legacy object (written via fuse/glfs): hash what we are
            # about to serve — this pays the one join the SG lane
            # otherwise avoids, so it is the fallback, not the norm
            etag = hashlib.sha256(
                data if isinstance(data, (bytes, bytearray))
                else bytes(data)).hexdigest()
            base_headers["etag"] = f'"{etag}"'
        if self._ocache is not None:
            await self._fill_cache(c, path, ia, etag, data)
        return await self._respond(writer, 200, base_headers, data)

    # -- introspection -----------------------------------------------------

    def dump(self) -> dict:
        return {"host": self.host, "port": self.port,
                "volume": self.volume, "conns": self.conns,
                "inflight": self.inflight,
                "pool": self.pool.size,
                "max_clients": self.max_clients,
                "requests": {f"{m} {s}": v for (m, s), v
                             in sorted(self.requests.items())},
                "bytes_rx": self.bytes_rx, "bytes_tx": self.bytes_tx,
                "throttled": self.throttled,
                "body_writes": dict(self.body_writes),
                "sg_segments": self.sg_segments,
                "etag_fast_hits": self.etag_fast_hits,
                "etag_invalidations": self.etag_invalidations,
                "object_cache": self._ocache.dump()
                if self._ocache is not None else None,
                "qos": {"enabled": True, **self._qos_opts,
                        "shed": self._qos.stats["shed"],
                        "shaped_clients": self._qos.shaped_count()}
                if self._qos is not None else None,
                "events": dict(self.events)}
