"""Gateway daemon entry point (glusterd's spawner runs this):

    python -m glusterfs_tpu.gateway --glusterd 127.0.0.1:24007 \
        --volume vol0 --listen 0 --portfile /tmp/gw.port

Each pool member is a full managed mount (GETSPEC + volfile watcher),
so live ``volume set`` changes reconfigure the gateway's graphs the
same way they reconfigure a fuse mount.  ``--volfile`` serves a raw
volfile instead (tests / standalone use — no watcher, no glusterd).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys

from ..core import events as gf_events
from ..core import gflog
from .server import ClientPool, ObjectGateway

log = gflog.get_logger("gateway.daemon")


async def _amain(args) -> None:
    if args.eventsd:
        gf_events.configure(args.eventsd)

    if args.volfile:
        with open(args.volfile) as f:
            text = f.read()

        async def factory():
            from ..api.glfs import Client, wait_connected
            from ..core.graph import Graph

            graph = Graph.construct(text)
            client = Client(graph)
            await client.mount()
            await wait_connected(graph)
            return client
    else:
        host, _, port = args.glusterd.rpartition(":")
        gd_host, gd_port = host or "127.0.0.1", int(port)

        async def factory():
            from ..mgmt.glusterd import mount_volume

            return await mount_volume(gd_host, gd_port, args.volume)

    gw = ObjectGateway(ClientPool(factory, args.pool),
                       host=args.host, port=args.listen,
                       max_clients=args.max_clients,
                       volume=args.volume or args.volfile)
    await gw.start()
    if args.portfile:
        tmp = args.portfile + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(gw.port))
        os.replace(tmp, args.portfile)
    metrics_srv = None
    if args.metrics_port:
        from ..daemon import serve_metrics

        metrics_srv = await serve_metrics(args.host, args.metrics_port)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if metrics_srv is not None:
        metrics_srv.close()
    await gw.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-gateway")
    p.add_argument("--glusterd", default="127.0.0.1:24007",
                   help="mgmt endpoint for GETSPEC (ignored with "
                        "--volfile)")
    p.add_argument("--volume", default="",
                   help="managed volume to serve")
    p.add_argument("--volfile", default="",
                   help="serve a raw client volfile instead of a "
                        "managed volume")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--listen", type=int, default=0,
                   help="HTTP port (0 = ephemeral)")
    p.add_argument("--portfile", default="",
                   help="write the bound port here")
    p.add_argument("--pool", type=int, default=4,
                   help="glfs client pool size (gateway.pool-size)")
    p.add_argument("--max-clients", type=int, default=512,
                   help="connection admission limit "
                        "(gateway.max-clients)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve the unified metrics registry on this "
                        "port (0 = off)")
    p.add_argument("--eventsd", default="",
                   help="host:port of gftpu-eventsd (arms GATEWAY_* "
                        "lifecycle events; GFTPU_EVENTSD also works)")
    args = p.parse_args(argv)
    if not args.volume and not args.volfile:
        p.error("one of --volume / --volfile is required")
    asyncio.run(_amain(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
