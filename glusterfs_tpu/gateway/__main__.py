"""Gateway daemon entry point (glusterd's spawner runs this):

    python -m glusterfs_tpu.gateway --glusterd 127.0.0.1:24007 \
        --volume vol0 --listen 0 --portfile /tmp/gw.port

Each pool member is a full managed mount (GETSPEC + volfile watcher),
so live ``volume set`` changes reconfigure the gateway's graphs the
same way they reconfigure a fuse mount.  ``--volfile`` serves a raw
volfile instead (tests / standalone use — no watcher, no glusterd).

Three roles (ISSUE 12):

* ``--workers 0`` (default): the single-process gateway — one event
  loop serves the port directly (the pre-op-version-14 shape).
* ``--workers N``: this process becomes the worker-pool SUPERVISOR —
  it owns the port (SO_REUSEPORT reservation, or accept + SCM_RIGHTS
  fd passing under ``--fd-pass``/old kernels), spawns N shared-nothing
  worker processes, respawns crashes, fans SIGTERM out, and serves the
  AGGREGATED metrics on ``--metrics-port``.
* ``--worker-fd FD`` (internal): spawned BY a supervisor — runs one
  worker's gateway with its own event loop, glfs pool, and metrics
  registry shard, talking to the parent over the control socketpair.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys

from ..core import events as gf_events
from ..core import flight, gflog
from .server import ClientPool, ObjectGateway

log = gflog.get_logger("gateway.daemon")


def _pool_factory(args):
    """The glfs mount factory shared by every role — each CALL is one
    private graph, so workers (separate processes) and pool members
    (same process) alike own their wire connections outright."""
    if args.volfile:
        with open(args.volfile) as f:
            text = f.read()

        async def factory():
            from ..api.glfs import Client, wait_connected
            from ..core.graph import Graph

            graph = Graph.construct(text)
            client = Client(graph)
            await client.mount()
            await wait_connected(graph)
            return client
    else:
        host, _, port = args.glusterd.rpartition(":")
        gd_host, gd_port = host or "127.0.0.1", int(port)

        async def factory():
            from ..mgmt.glusterd import mount_volume

            return await mount_volume(gd_host, gd_port, args.volume)
    return factory


def _object_cache_bytes(args) -> int:
    from ..core.options import parse_size

    return parse_size(args.object_cache)


def _qos_kw(args) -> dict:
    from ..core.options import parse_size, parse_time

    return {"qos_fops": float(args.qos_fops),
            "qos_bytes": float(parse_size(args.qos_bytes)),
            "qos_burst": float(parse_time(args.qos_burst))}


async def _amain_single(args) -> None:
    gw = ObjectGateway(ClientPool(_pool_factory(args), args.pool),
                       host=args.host, port=args.listen,
                       max_clients=args.max_clients,
                       volume=args.volume or args.volfile,
                       object_cache_size=_object_cache_bytes(args),
                       **_qos_kw(args))
    await gw.start()
    if args.portfile:
        tmp = args.portfile + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(gw.port))
        os.replace(tmp, args.portfile)
    metrics_srv = None
    if args.metrics_port:
        from ..daemon import serve_metrics

        metrics_srv = await serve_metrics(args.host, args.metrics_port)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if metrics_srv is not None:
        metrics_srv.close()
    await gw.stop()


async def _amain_worker(args) -> None:
    from .workers import worker_serve

    gw = ObjectGateway(ClientPool(_pool_factory(args), args.pool),
                       host=args.host, port=args.listen,
                       max_clients=args.max_clients,
                       volume=args.volume or args.volfile,
                       object_cache_size=_object_cache_bytes(args),
                       **_qos_kw(args))
    await worker_serve(gw, args.worker_fd, args.worker_rank,
                       args.reuseport, args.host, args.listen)


async def _amain_supervisor(args) -> None:
    from .workers import GatewaySupervisor

    base_argv = [sys.executable, "-m", "glusterfs_tpu.gateway",
                 "--pool", str(args.pool),
                 # per-worker budget: shared-nothing workers each own a
                 # full cache (their own pool clients hold the leases
                 # that keep it coherent)
                 "--object-cache", str(_object_cache_bytes(args)),
                 # per-worker buckets too: a peer's rate is enforced by
                 # whichever worker its connections land on, so with a
                 # multi-connection peer striped across N workers the
                 # pool-wide ceiling is up to N x the configured rate
                 # (documented in docs/qos.md; same shared-nothing
                 # trade the cache makes)
                 "--qos-fops", str(args.qos_fops),
                 "--qos-bytes", str(args.qos_bytes),
                 "--qos-burst", str(args.qos_burst)]
    if args.volfile:
        base_argv += ["--volfile", args.volfile]
    else:
        base_argv += ["--glusterd", args.glusterd,
                      "--volume", args.volume]
    if args.eventsd:
        base_argv += ["--eventsd", args.eventsd]
    sup = GatewaySupervisor(
        base_argv, host=args.host, port=args.listen,
        workers=args.workers, max_clients=args.max_clients,
        metrics_port=args.metrics_port, portfile=args.portfile,
        statusfile=args.statusfile, force_fd_pass=args.fd_pass)
    await sup.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    await sup.stop()


async def _amain(args) -> None:
    from ..core import history
    from ..core.metrics import register_build_info

    if args.eventsd:
        gf_events.configure(args.eventsd)
    if args.worker_fd >= 0:
        flight.set_role("gateway-worker")
        register_build_info("gateway-worker")
        history.arm()
        await _amain_worker(args)
    elif args.workers > 0:
        # the supervisor mounts no volfile, so the diagnostics.* keys
        # never reach it through io-stats — its capture arm is argv
        # (worker-respawn auto-capture writes the pool's bundle here;
        # its history ring samples its own registry, while the
        # aggregated /metrics/history.json merges the WORKER rings)
        flight.set_role("gateway-supervisor")
        register_build_info("gateway-supervisor")
        history.arm()
        if args.incident_dir:
            flight.configure_capture(incident_dir=args.incident_dir)
        await _amain_supervisor(args)
    else:
        flight.set_role("gateway")
        register_build_info("gateway")
        history.arm()
        await _amain_single(args)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-gateway")
    p.add_argument("--glusterd", default="127.0.0.1:24007",
                   help="mgmt endpoint for GETSPEC (ignored with "
                        "--volfile)")
    p.add_argument("--volume", default="",
                   help="managed volume to serve")
    p.add_argument("--volfile", default="",
                   help="serve a raw client volfile instead of a "
                        "managed volume")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--listen", type=int, default=0,
                   help="HTTP port (0 = ephemeral)")
    p.add_argument("--portfile", default="",
                   help="write the bound port here")
    p.add_argument("--pool", type=int, default=4,
                   help="glfs client pool size (gateway.pool-size; "
                        "per worker when --workers is set)")
    p.add_argument("--max-clients", type=int, default=512,
                   help="connection admission limit "
                        "(gateway.max-clients; the supervisor divides "
                        "it across workers at spawn)")
    p.add_argument("--object-cache", default="0",
                   help="lease-held object cache budget in bytes, "
                        "size suffixes accepted "
                        "(gateway.object-cache-size; 0 = off; per "
                        "worker when --workers is set)")
    p.add_argument("--qos-fops", type=float, default=0.0,
                   help="per-peer-IP request rate limit, fops/s "
                        "(server.qos-fops-per-sec; 0 = off; per "
                        "worker when --workers is set)")
    p.add_argument("--qos-bytes", default="0",
                   help="per-peer-IP payload rate limit, bytes/s, "
                        "size suffixes accepted "
                        "(server.qos-bytes-per-sec; 0 = off)")
    p.add_argument("--qos-burst", default="1",
                   help="bucket depth in seconds of the configured "
                        "rate, time suffixes accepted "
                        "(server.qos-burst)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve the unified metrics registry on this "
                        "port (0 = off; aggregated across workers "
                        "when --workers is set)")
    p.add_argument("--eventsd", default="",
                   help="host:port of gftpu-eventsd (arms GATEWAY_* "
                        "lifecycle events; GFTPU_EVENTSD also works)")
    p.add_argument("--workers", type=int, default=0,
                   help="shared-nothing worker processes "
                        "(gateway.workers; 0 = single-process)")
    p.add_argument("--fd-pass", action="store_true",
                   help="force the parent-accepts + SCM_RIGHTS "
                        "fd-passing lane instead of SO_REUSEPORT")
    p.add_argument("--statusfile", default="",
                   help="supervisor writes worker pids/mode here")
    p.add_argument("--incident-dir", default="",
                   help="supervisor auto-capture directory for "
                        "incident bundles (diagnostics.incident-dir "
                        "for the role that mounts no volfile)")
    p.add_argument("--worker-fd", type=int, default=-1,
                   help=argparse.SUPPRESS)  # internal: control channel
    p.add_argument("--worker-rank", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--reuseport", action="store_true",
                   help=argparse.SUPPRESS)  # internal: bind own socket
    args = p.parse_args(argv)
    if not args.volume and not args.volfile:
        p.error("one of --volume / --volfile is required")
    asyncio.run(_amain(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
