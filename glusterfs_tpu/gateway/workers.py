"""Shared-nothing gateway worker pool (ISSUE 12).

Every concurrency record so far flatlined at the same wall: ONE CPython
interpreter turns all gateway frames, so the c1->c512 ladder sits flat
at the single-core frame-turning floor (docs/event_threads.md GIL
analysis).  This module breaks that floor the way nginx/envoy do — by
not sharing the interpreter at all:

* ``gateway.workers = N`` forks N **worker processes**.  Each worker
  owns its own event loop, its own glfs :class:`ClientPool` (so its own
  wire connections and upcall sinks), and its own metrics registry
  shard — shared-nothing; the GIL stops being a cross-request
  bottleneck because there is no shared interpreter left to contend on.

* **Socket plane**: every worker ``bind()``s the same port with
  ``SO_REUSEPORT`` and the kernel load-balances accepted connections
  across them (the reference's many-glusterfsd analog).  On kernels
  without usable reuseport distribution — or under ``--fd-pass`` — the
  parent accepts and hands connection fds to workers over a
  ``socketpair`` with ``SCM_RIGHTS`` (the classic pre-fork fd-passing
  fallback), round-robin.

* **Supervision**: the parent is a supervisor, not a data path.  A
  crashed worker is respawned (same rank, fresh channel); SIGTERM fans
  out; admission control (``gateway.max-clients``) is divided across
  workers at spawn so the pool as a whole honors the volume key.

* **Metrics**: each worker's registry shard is scraped over its control
  channel; the parent aggregates per-worker snapshots (counters sum,
  gauges sum, quantile gauges take the max) and serves the merged
  families on ``gateway.metrics-port`` (text + ``/metrics.json``) —
  plus its own ``gftpu_gateway_workers`` / worker-respawn families.

Control channel: one ``AF_UNIX`` ``SOCK_SEQPACKET`` socketpair per
worker carrying JSON messages (fd in ancillary data for ``conn``):

    parent -> worker   {"op": "conn"} + fd          (fd-pass mode)
    parent -> worker   {"op": "snapshot", "id": n}
    parent -> worker   {"op": "history", "id": n, "window": w|null}
    parent -> worker   {"op": "alerts", "id": n}
    worker -> parent   {"op": "ready", "port": p}
    worker -> parent   {"op": "snapshot", "id": n, "registry": ...,
                        "gateway": ...}
    worker -> parent   {"op": "history"|"alerts", "id": n, ...}

Channel EOF means the peer died: the worker exits (orphan guard), the
parent respawns.
"""

from __future__ import annotations

import array
import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time

from ..core import flight, gflog
from ..core.events import gf_event
from ..core.metrics import render_families

log = gflog.get_logger("gateway.workers")

#: seqpacket message ceiling ASKED FOR at channel creation — a worker
#: registry snapshot is a few KiB.  The kernel silently clamps
#: SO_SNDBUF to net.core.wmem_max, so the EFFECTIVE cap is read back
#: per socket (recv buffers size to it, and an EMSGSIZE send degrades
#: to a truncated reply — never a dead worker)
_BUFSIZE = 4 << 20

_READY_TIMEOUT_S = 120.0  # cold interpreter + jax imports + pool mounts
_SNAPSHOT_TIMEOUT_S = 5.0


def reuseport_ok(host: str) -> bool:
    """Can two sockets bind the same (host, port) with SO_REUSEPORT on
    this kernel?  Probed, not assumed — the fallback exists for kernels
    that lack it (or lack the load-balancing semantics)."""
    if not hasattr(socket, "SO_REUSEPORT"):
        return False
    s1 = s2 = None
    try:
        s1 = bind_reuseport(host, 0)
        s2 = bind_reuseport(host, s1.getsockname()[1])
        return True
    except OSError:
        return False
    finally:
        for s in (s1, s2):
            if s is not None:
                s.close()


def bind_reuseport(host: str, port: int) -> socket.socket:
    """A bound (not yet listening) SO_REUSEPORT socket."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((host, port))
    except BaseException:
        s.close()
        raise
    return s


def make_channel() -> tuple[socket.socket, socket.socket]:
    """The per-worker control socketpair (seqpacket: message-framed
    JSON, SCM_RIGHTS rides the ``conn`` messages)."""
    a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_SEQPACKET)
    for s in (a, b):
        s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, _BUFSIZE)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, _BUFSIZE)
    return a, b


async def _wait_io(loop, sock: socket.socket, write: bool) -> None:
    fut = loop.create_future()
    fd = sock.fileno()
    add, remove = ((loop.add_writer, loop.remove_writer) if write
                   else (loop.add_reader, loop.remove_reader))

    def ready():
        if not fut.done():
            fut.set_result(None)

    add(fd, ready)
    try:
        await fut
    finally:
        remove(fd)


async def send_msg(loop, sock: socket.socket, obj: dict,
                   fds: tuple[int, ...] = ()) -> None:
    """One JSON message (+ optional fds) as one seqpacket datagram."""
    payload = json.dumps(obj).encode()
    anc = [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
            array.array("i", fds))] if fds else []
    while True:
        try:
            sock.sendmsg([payload], anc)
            return
        except BlockingIOError:
            await _wait_io(loop, sock, write=True)


async def recv_msg(loop, sock: socket.socket
                   ) -> tuple[dict | None, list[int]]:
    """One message; ``(None, [])`` on EOF.  Received fds are returned
    raw (caller owns closing them).  The receive buffer is sized to
    the socket's EFFECTIVE buffer (getsockopt), not the 4 MiB ask — a
    4 MiB bytes alloc per ~20-byte ``conn`` message would churn
    gigabytes on a busy fd-pass accept path."""
    bufsize = max(64 << 10,
                  sock.getsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF))
    while True:
        try:
            data, anc, _flags, _addr = sock.recvmsg(
                bufsize, socket.CMSG_LEN(4 * 8))
            break
        except BlockingIOError:
            await _wait_io(loop, sock, write=False)
    fds: list[int] = []
    for level, ctype, cdata in anc:
        if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
            a = array.array("i")
            a.frombytes(cdata[: len(cdata) - len(cdata) % a.itemsize])
            fds.extend(a)
    if not data:
        return None, fds
    return json.loads(data.decode()), fds


def merge_snapshots(snaps: list[dict]) -> dict:
    """Aggregate per-worker registry snapshots into one family dict
    (the ``MetricsRegistry.snapshot()`` shape).  Counters and plain
    gauges SUM across the shards (each worker counts only its own
    traffic); gauge samples carrying a ``quantile`` label take the MAX
    (summing percentiles across shards is meaningless — the max is the
    honest worst-shard view)."""
    merged: dict[str, dict] = {}
    for snap in snaps:
        for name, fam in snap.items():
            m = merged.setdefault(
                name, {"type": fam.get("type", "gauge"),
                       "help": fam.get("help", ""), "samples": {}})
            for labels, value in fam.get("samples", []):
                key = tuple(sorted(labels.items()))
                if "quantile" in labels:
                    prev = m["samples"].get(key)
                    m["samples"][key] = value if prev is None \
                        else max(prev, value)
                else:
                    m["samples"][key] = m["samples"].get(key, 0) + value
    return {name: {"type": fam["type"], "help": fam["help"],
                   "samples": [[dict(k), v]
                               for k, v in sorted(fam["samples"].items())]}
            for name, fam in sorted(merged.items())}


class _Worker:
    """Parent-side handle of one worker process."""

    def __init__(self, rank: int, proc: subprocess.Popen,
                 chan: socket.socket):
        self.rank = rank
        self.proc = proc
        self.chan = chan
        self.ready = asyncio.get_running_loop().create_future()
        self.port = 0
        self._waiters: dict[int, asyncio.Future] = {}
        self._reader: asyncio.Task | None = None

    def start_reader(self, loop) -> None:
        self._reader = loop.create_task(self._read_loop(loop))

    async def _read_loop(self, loop) -> None:
        try:
            while True:
                msg, fds = await recv_msg(loop, self.chan)
                for fd in fds:  # workers never send fds; be safe
                    os.close(fd)
                if msg is None:
                    break
                if msg.get("op") == "ready":
                    self.port = int(msg.get("port", 0))
                    if not self.ready.done():
                        self.ready.set_result(True)
                elif "id" in msg:  # snapshot / history / alerts reply
                    fut = self._waiters.pop(msg.get("id"), None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except Exception:  # noqa: BLE001 - channel torn: worker is gone
            pass
        finally:
            if not self.ready.done():
                self.ready.set_result(False)
            for fut in self._waiters.values():
                if not fut.done():
                    fut.set_result(None)
            self._waiters.clear()

    async def call(self, loop, req_id: int, op: str,
                   **extra) -> dict | None:
        """One request/reply round on the control channel (snapshot /
        history / alerts all share the waiter plumbing)."""
        fut = loop.create_future()
        self._waiters[req_id] = fut
        try:
            await send_msg(loop, self.chan,
                           {"op": op, "id": req_id, **extra})
            return await asyncio.wait_for(fut, _SNAPSHOT_TIMEOUT_S)
        except (OSError, asyncio.TimeoutError):
            self._waiters.pop(req_id, None)
            return None

    async def snapshot(self, loop, req_id: int) -> dict | None:
        return await self.call(loop, req_id, "snapshot")

    def alive(self) -> bool:
        return self.proc.poll() is None

    def close(self) -> None:
        if self._reader is not None:
            self._reader.cancel()
        try:
            self.chan.close()
        except OSError:
            pass


class GatewaySupervisor:
    """The parent process of a ``gateway.workers`` pool.

    Owns the port (reserving it or accepting on it), the worker
    lifecycle (spawn / respawn / SIGTERM fan-out), and the aggregated
    metrics endpoint.  It serves no HTTP itself — the data plane lives
    entirely in the workers."""

    def __init__(self, base_argv: list[str], host: str, port: int,
                 workers: int, max_clients: int,
                 metrics_port: int = 0, portfile: str = "",
                 statusfile: str = "", force_fd_pass: bool = False):
        self.base_argv = list(base_argv)
        self.host = host
        self.port = int(port)
        self.workers = max(1, int(workers))
        self.max_clients = int(max_clients)
        self.metrics_port = int(metrics_port)
        self.portfile = portfile
        self.statusfile = statusfile
        self.force_fd_pass = bool(force_fd_pass)
        self.mode = ""  # "reuseport" | "fd-pass"
        self.respawns = 0
        self._workers: dict[int, _Worker] = {}
        self._reserve: socket.socket | None = None
        self._lsock: socket.socket | None = None
        self._tasks: list[asyncio.Task] = []
        self._metrics_srv: asyncio.AbstractServer | None = None
        self._stopping = False
        self._snap_seq = 0
        self._rr = 0
        self._last_respawn: dict[int, float] = {}

    # -- lifecycle ---------------------------------------------------------

    def per_worker_clients(self) -> int:
        """The admission split: the volume key bounds the POOL, so each
        worker enforces its share (never below 1)."""
        return max(1, self.max_clients // self.workers)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        if not self.force_fd_pass and reuseport_ok(self.host):
            self.mode = "reuseport"
            # reserve the port for the pool's lifetime: bound but NEVER
            # listening, so the kernel's reuseport distribution only
            # ever sees the workers' listening sockets
            self._reserve = bind_reuseport(self.host, self.port)
            self.port = self._reserve.getsockname()[1]
        else:
            self.mode = "fd-pass"
            self._lsock = socket.socket(socket.AF_INET,
                                        socket.SOCK_STREAM)
            self._lsock.setsockopt(socket.SOL_SOCKET,
                                   socket.SO_REUSEADDR, 1)
            self._lsock.bind((self.host, self.port))
            self._lsock.listen(512)
            self._lsock.setblocking(False)
            self.port = self._lsock.getsockname()[1]
        for rank in range(self.workers):
            self._spawn(rank)
        ok = await asyncio.gather(
            *(asyncio.wait_for(w.ready, _READY_TIMEOUT_S)
              for w in self._workers.values()),
            return_exceptions=True)
        if not any(r is True for r in ok):
            raise RuntimeError(
                f"no gateway worker came up (of {self.workers})")
        if self.mode == "fd-pass":
            self._tasks.append(loop.create_task(self._accept_loop(loop)))
        self._tasks.append(loop.create_task(self._supervise(loop)))
        if self.metrics_port:
            from ..daemon import http_route_handler

            async def text():
                return (render_families(await self.snapshot()).encode(),
                        b"text/plain; version=0.0.4")

            async def structured():
                return (json.dumps(await self.snapshot()).encode(),
                        b"application/json")

            async def per_worker():
                return (json.dumps({
                    "mode": self.mode, "respawns": self.respawns,
                    "workers": await self.gateway_dumps()}).encode(),
                    b"application/json")

            async def incident_json():
                return (json.dumps(await self.incident(),
                                   default=repr).encode(),
                        b"application/json")

            async def history_json():
                return (json.dumps(await self.history(),
                                   default=repr).encode(),
                        b"application/json")

            async def alerts_json():
                return (json.dumps(await self.alerts(),
                                   default=repr).encode(),
                        b"application/json")

            self._metrics_srv = await asyncio.start_server(
                http_route_handler({"/metrics": text, "/": text,
                                    "/metrics.json": structured,
                                    "/workers.json": per_worker,
                                    "/incident.json": incident_json,
                                    "/metrics/history.json": history_json,
                                    "/alerts.json": alerts_json}),
                self.host, self.metrics_port)
        if self.portfile:
            tmp = self.portfile + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(self.port))
            os.replace(tmp, self.portfile)
        self._write_status()
        log.info(2, "gateway worker pool on %s:%d (%d workers, %s, "
                 "%d clients/worker)", self.host, self.port,
                 self.workers, self.mode, self.per_worker_clients())

    def _spawn(self, rank: int) -> None:
        parent_sock, child_sock = make_channel()
        parent_sock.setblocking(False)
        argv = self.base_argv + [
            "--worker-fd", str(child_sock.fileno()),
            "--worker-rank", str(rank),
            "--host", self.host,
            "--listen", str(self.port),
            "--max-clients", str(self.per_worker_clients()),
        ]
        if self.mode == "reuseport":
            argv.append("--reuseport")
        proc = subprocess.Popen(argv, pass_fds=(child_sock.fileno(),),
                                stdout=subprocess.DEVNULL)
        child_sock.close()
        w = _Worker(rank, proc, parent_sock)
        w.start_reader(asyncio.get_running_loop())
        self._workers[rank] = w

    async def _supervise(self, loop) -> None:
        """Respawn crashed workers; a dying worker loses its in-flight
        connections (its clients reconnect and land on a live sibling)
        but never the pool."""
        while not self._stopping:
            await asyncio.sleep(0.3)
            for rank, w in list(self._workers.items()):
                if self._stopping or w.alive():
                    continue
                # backoff: a worker dying INSTANTLY (bad config, port
                # gone) must not crash-loop at poll rate — one respawn
                # per rank per second bounds the spawn storm while a
                # healthy-but-crashed worker still returns fast
                now = time.monotonic()
                if now - self._last_respawn.get(rank, 0.0) < 1.0:
                    continue
                self._last_respawn[rank] = now
                log.warning(2, "gateway worker %d died (rc=%s); "
                            "respawning", rank, w.proc.returncode)
                w.close()
                self.respawns += 1
                # failure-class event: the gf_event tap lands it in the
                # flight ring AND auto-captures an incident bundle when
                # --incident-dir armed capture (core/flight.py)
                gf_event("GATEWAY_WORKER_RESPAWN", rank=rank,
                         rc=w.proc.returncode, respawns=self.respawns)
                self._spawn(rank)
                self._write_status()

    async def _accept_loop(self, loop) -> None:
        """fd-pass mode: accept here, hand the connection fd to the
        next live worker over SCM_RIGHTS, close our copy."""
        while not self._stopping:
            try:
                conn, _addr = await loop.sock_accept(self._lsock)
            except (OSError, asyncio.CancelledError):
                break
            sent = False
            workers = [w for w in self._workers.values() if w.alive()]
            for i in range(len(workers)):
                w = workers[(self._rr + i) % len(workers)]
                try:
                    await send_msg(loop, w.chan, {"op": "conn"},
                                   fds=(conn.fileno(),))
                    self._rr = (self._rr + i + 1) % max(1, len(workers))
                    sent = True
                    break
                except OSError:
                    continue
            conn.close()  # worker holds its own duplicate now
            if not sent:
                log.warning(3, "no live worker to take a connection")

    # -- aggregated metrics ------------------------------------------------

    async def snapshot(self) -> dict:
        """Merged per-worker registry snapshots + supervisor families."""
        loop = asyncio.get_running_loop()
        reqs = []
        for w in list(self._workers.values()):
            if w.alive():
                self._snap_seq += 1
                reqs.append(w.snapshot(loop, self._snap_seq))
        replies = await asyncio.gather(*reqs) if reqs else []
        shards = [r["registry"] for r in replies
                  if r and "registry" in r]
        merged = merge_snapshots(shards)
        alive = sum(1 for w in self._workers.values() if w.alive())
        merged["gftpu_gateway_workers"] = {
            "type": "gauge",
            "help": "shared-nothing gateway worker processes by state "
                    "(mode label says reuseport vs fd-pass)",
            "samples": [[{"state": "alive", "mode": self.mode}, alive],
                        [{"state": "configured", "mode": self.mode},
                         self.workers]]}
        merged["gftpu_gateway_worker_respawns_total"] = {
            "type": "counter",
            "help": "gateway workers respawned after a crash",
            "samples": [[{}, self.respawns]]}
        # the supervisor's own identity rides the merged scrape next to
        # the workers' (whose role="gateway-worker" samples SUM to the
        # live-shard count — an honest process census for an info gauge)
        from .. import OP_VERSION, __version__
        bi = merged.setdefault("gftpu_build_info", {
            "type": "gauge",
            "help": "build/version identity of this process "
                    "(value is always 1)",
            "samples": []})
        bi["samples"].append([{"version": __version__,
                               "op_version": str(OP_VERSION),
                               "role": "gateway-supervisor"}, 1])
        return merged

    async def history(self, window: float | None = None) -> dict:
        """Merged per-worker history rings (``/metrics/history.json``
        on the aggregated endpoint): the same counters-sum /
        quantiles-max semantics as the snapshot merge, applied per grid
        timestamp by :func:`core.history.merge_series`."""
        from ..core import history as _history

        loop = asyncio.get_running_loop()
        reqs = []
        for w in list(self._workers.values()):
            if w.alive():
                self._snap_seq += 1
                reqs.append(w.call(loop, self._snap_seq, "history",
                                   window=window))
        replies = await asyncio.gather(*reqs) if reqs else []
        dumps = [r["history"] for r in replies
                 if r and isinstance(r.get("history"), dict)]
        merged = _history.merge_series(dumps)
        merged["mode"] = self.mode
        merged["offline"] = len(reqs) - len(dumps)
        return merged

    async def alerts(self) -> dict:
        """Per-worker SLO engine status union (``/alerts.json``): the
        active set is the union across shards (rank-tagged), a dead
        worker is NAMED offline — the volume-status partial contract."""
        loop = asyncio.get_running_loop()
        out: dict = {"role": "gateway-supervisor", "active": [],
                     "history": [], "offline": []}
        for w in sorted(self._workers.values(), key=lambda x: x.rank):
            if not w.alive():
                out["offline"].append(w.rank)
                continue
            self._snap_seq += 1
            r = await w.call(loop, self._snap_seq, "alerts")
            st = (r or {}).get("alerts")
            if not isinstance(st, dict):
                out["offline"].append(w.rank)
                continue
            for a in st.get("active", []):
                out["active"].append({"rank": w.rank, **a})
            for t in st.get("history", []):
                out["history"].append({"rank": w.rank, **t})
        out["active"].sort(key=lambda a: a.get("since", 0.0))
        out["history"].sort(key=lambda t: t.get("ts", 0.0))
        return out

    async def incident(self) -> dict:
        """The pool's incident bundle: the supervisor's own flight
        snapshot plus every live worker's flight bundle + registry
        shard over the control channel; a dead worker is NAMED offline,
        never silently dropped (the volume-status partial contract)."""
        loop = asyncio.get_running_loop()
        out: dict = {"role": "gateway-supervisor",
                     "mode": self.mode, "respawns": self.respawns,
                     "supervisor": flight.snapshot(),
                     "workers": []}
        for w in sorted(self._workers.values(), key=lambda x: x.rank):
            if not w.alive():
                out["workers"].append({"rank": w.rank,
                                       "offline": True})
                continue
            self._snap_seq += 1
            r = await w.snapshot(loop, self._snap_seq)
            if r is None:
                out["workers"].append({"rank": w.rank,
                                       "offline": True})
                continue
            row = {"rank": w.rank, "pid": w.proc.pid,
                   "flight": r.get("flight") or {},
                   "registry": r.get("registry") or {}}
            if r.get("truncated"):
                row["truncated"] = r["truncated"]
            out["workers"].append(row)
        return out

    async def gateway_dumps(self) -> list[dict]:
        """Per-worker ObjectGateway.dump() list (tests/status)."""
        loop = asyncio.get_running_loop()
        out = []
        for w in list(self._workers.values()):
            if not w.alive():
                continue
            self._snap_seq += 1
            r = await w.snapshot(loop, self._snap_seq)
            if r and "gateway" in r:
                out.append({"rank": w.rank, **r["gateway"]})
        return out

    def _write_status(self) -> None:
        if not self.statusfile:
            return
        info = {"pid": os.getpid(), "port": self.port,
                "mode": self.mode, "respawns": self.respawns,
                "workers": [
                    {"rank": w.rank, "pid": w.proc.pid,
                     "alive": w.alive()}
                    for w in sorted(self._workers.values(),
                                    key=lambda x: x.rank)]}
        tmp = self.statusfile + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(info, f)
            os.replace(tmp, self.statusfile)
        except OSError:
            pass

    # -- teardown ----------------------------------------------------------

    async def stop(self) -> None:
        """SIGTERM fan-out, bounded wait, SIGKILL stragglers."""
        self._stopping = True
        for t in self._tasks:
            t.cancel()
        for w in self._workers.values():
            if w.alive():
                try:
                    w.proc.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 5.0
        for w in self._workers.values():
            left = deadline - time.monotonic()
            try:
                # off-loop: the supervisor's loop stays live (metrics
                # scrapes, accept teardown) while workers drain
                await asyncio.to_thread(w.proc.wait,
                                        timeout=max(0.1, left))
            except subprocess.TimeoutExpired:
                w.proc.kill()
                await asyncio.to_thread(w.proc.wait)
            w.close()
        self._workers.clear()
        if self._metrics_srv is not None:
            self._metrics_srv.close()
            self._metrics_srv = None
        for s in (self._reserve, self._lsock):
            if s is not None:
                s.close()
        self._reserve = self._lsock = None
        if self.portfile:
            try:
                os.unlink(self.portfile)
            except FileNotFoundError:
                pass


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------


async def worker_serve(gw, ctl_fd: int, rank: int,
                       reuseport: bool, host: str, port: int) -> None:
    """One worker's life: start the gateway (own listener under
    reuseport, none under fd-pass), answer the control channel, exit
    when the channel closes (parent died) or SIGTERM lands.

    ``gw`` is this worker's own :class:`ObjectGateway` — its pool, its
    event loop, its registry shard; nothing here is shared with any
    sibling."""
    loop = asyncio.get_running_loop()
    chan = socket.socket(fileno=ctl_fd)
    chan.setblocking(False)
    stop = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)

    if reuseport:
        lsock = bind_reuseport(host, port)
        lsock.listen(128)
        await gw.start(sock=lsock)
    else:
        await gw.start(listen=False)
        gw.port = port  # the parent's listener; dumps stay truthful

    # strong refs to passed-fd serve tasks: the loop keeps only weak
    # refs, and a GC'd task resets its client's connection mid-request
    serving: set[asyncio.Task] = set()

    async def read_ctl():
        try:
            await _read_ctl_loop()
        except Exception:  # noqa: BLE001 - channel torn any other way
            # ECONNRESET (supervisor SIGKILLed with data in flight) or
            # a corrupt datagram must ALSO trip the orphan guard — a
            # dead reader task without stop.set() leaves a zombie
            # worker sharing the reuseport distribution forever
            stop.set()

    async def _read_ctl_loop():
        while True:
            msg, fds = await recv_msg(loop, chan)
            if msg is None:
                for fd in fds:
                    os.close(fd)
                stop.set()  # parent gone: orphaned workers must exit
                return
            op = msg.get("op")
            if op == "conn":
                for fd in fds:
                    conn = socket.socket(fileno=fd)
                    try:
                        r, w = await asyncio.open_connection(sock=conn)
                    except OSError:
                        conn.close()
                        continue
                    t = loop.create_task(gw._serve_conn(r, w))
                    serving.add(t)
                    t.add_done_callback(serving.discard)
            elif op == "snapshot":
                import errno as _errno

                from ..core.metrics import REGISTRY

                for fd in fds:
                    os.close(fd)
                try:
                    await send_msg(loop, chan, {
                        "op": "snapshot", "id": msg.get("id"),
                        "registry": REGISTRY.snapshot(),
                        # this worker's flight bundle rides the same
                        # reply (metrics=False: "registry" above is
                        # already the scrape) so the supervisor's
                        # incident merge sees every shard's ring
                        "flight": flight.snapshot(metrics=False),
                        "gateway": gw.dump()})
                except OSError as e:
                    if e.errno != _errno.EMSGSIZE:
                        stop.set()  # channel truly dead
                        return
                    # the shard outgrew the channel's effective
                    # message cap (wmem_max clamp): degrade the REPLY
                    # — a metrics scrape must never kill a worker
                    try:
                        await send_msg(loop, chan, {
                            "op": "snapshot", "id": msg.get("id"),
                            "registry": {},
                            "truncated": "registry snapshot exceeded "
                                         "the control channel's "
                                         "message cap",
                            "flight": flight.snapshot(
                                spans=50, records=50, metrics=False),
                            "gateway": gw.dump()})
                    except OSError:
                        stop.set()
                        return
            elif op == "history":
                from ..core import history as _history

                for fd in fds:
                    os.close(fd)
                win = msg.get("window")
                dump = _history.HISTORY.dump(
                    window=float(win) if win else None)
                try:
                    await send_msg(loop, chan, {
                        "op": "history", "id": msg.get("id"),
                        "history": dump})
                except OSError:
                    # a ring outgrowing the channel cap degrades to the
                    # bounded tail — a history scrape must never kill a
                    # worker (the snapshot EMSGSIZE contract)
                    try:
                        await send_msg(loop, chan, {
                            "op": "history", "id": msg.get("id"),
                            "history": _history.HISTORY.dump(
                                max_samples=30)})
                    except OSError:
                        stop.set()
                        return
            elif op == "alerts":
                from ..core import slo as _slo

                for fd in fds:
                    os.close(fd)
                try:
                    await send_msg(loop, chan, {
                        "op": "alerts", "id": msg.get("id"),
                        "alerts": _slo.ENGINE.status()})
                except OSError:
                    stop.set()
                    return
            else:
                for fd in fds:
                    os.close(fd)

    reader = loop.create_task(read_ctl())
    try:
        await send_msg(loop, chan, {"op": "ready", "port": gw.port,
                                    "rank": rank})
    except OSError:
        stop.set()
    await stop.wait()
    reader.cancel()
    await gw.stop()
    try:
        chan.close()
    except OSError:
        pass
