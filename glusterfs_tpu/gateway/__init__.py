"""S3-flavored HTTP object gateway — a second front door for
many-client traffic (ROADMAP open item 4).

The reference ships whole alternate access stacks beside the fuse
mount (gNFS in xlators/nfs, gfapi consumers like NFS-Ganesha and
Samba-vfs); this package is that idea for the HTTP-object workload: an
asyncio HTTP/1.1 daemon speaking an S3-flavored dialect over pooled
:class:`api.glfs.Client` handles, so thousands of small concurrent
requests multiplex onto a handful of wire connections instead of one
kernel bridge.

See :mod:`glusterfs_tpu.gateway.server` for the dialect and
docs/object_gateway.md for the API tour, the coherence model against a
concurrent fuse client, and the GET-path copy census.
:mod:`glusterfs_tpu.gateway.workers` is the shared-nothing worker pool
(``gateway.workers``, docs/process_plane.md) that breaks the
one-interpreter frame-turning floor.
"""

from .server import ClientPool, ObjectGateway  # noqa: F401
from .workers import GatewaySupervisor  # noqa: F401
