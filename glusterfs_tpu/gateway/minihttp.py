"""Minimal HTTP/1.1 client for driving the gateway.

One copy shared by tests/test_gateway.py, bench.py's concurrency
ladder, and the ci.sh smoke stage — a dialect change (headers, chunked
bodies, HEAD semantics) lands everywhere at once instead of drifting
across three hand-rolled parsers.  Deliberately tiny: no redirects, no
TLS, no response streaming — exactly what driving the gateway needs.
"""

from __future__ import annotations

import asyncio


async def request(reader: asyncio.StreamReader,
                  writer: asyncio.StreamWriter, method: str,
                  target: str, headers: dict | None = None,
                  body: bytes = b"", chunks=None):
    """One request/response on an open connection (keep-alive safe).
    ``chunks`` sends the body chunked (the multipart-style streaming
    shape).  Returns ``(status, headers, body)``."""
    h = dict(headers or {})
    h.setdefault("host", "gw")
    if chunks is not None:
        h["transfer-encoding"] = "chunked"
    elif body or method in ("PUT", "POST"):
        h.setdefault("content-length", str(len(body)))
    writer.write((f"{method} {target} HTTP/1.1\r\n" + "".join(
        f"{k}: {v}\r\n" for k, v in h.items())
        + "\r\n").encode("latin-1"))
    if chunks is not None:
        for chunk in chunks:
            writer.write(f"{len(chunk):x}\r\n".encode()
                         + bytes(chunk) + b"\r\n")
        writer.write(b"0\r\n\r\n")
    else:
        writer.write(body)
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    resp_headers: dict[str, str] = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode("latin-1").partition(":")
        resp_headers[k.strip().lower()] = v.strip()
    n = int(resp_headers.get("content-length", 0))
    data = await reader.readexactly(n) if n and method != "HEAD" \
        else b""
    return status, resp_headers, data


async def fetch(host: str, port: int, method: str, target: str,
                headers: dict | None = None, body: bytes = b"",
                chunks=None):
    """One-shot request on its own connection (Connection: close)."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        h = {"connection": "close", **(headers or {})}
        return await request(reader, writer, method, target, h,
                             body, chunks)
    finally:
        writer.close()
