"""meta — the virtual ``.meta`` introspection tree on a mounted volume.

Reference: xlators/meta (meta.c:25-34, root-dir.c:17-26): a procfs-like
directory at the top of every client graph exposing the live graph,
each xlator's private state and options, and logging knobs; the
reference test suite reads files like
``.meta/graphs/active/<vol>-disperse-0/private`` as its introspection
oracle (tests/ec.rc:1-18) — statedump's interactive twin.

Virtual tree served here:

    /.meta/version                       package version
    /.meta/logging                       recent in-memory log ring
    /.meta/connections                   protocol/client transports +
                                         wire byte accounting
    /.meta/metrics                       unified registry text dump
    /.meta/graphs/active/<layer>/type    layer type name
    /.meta/graphs/active/<layer>/options validated live option values
    /.meta/graphs/active/<layer>/private dump_private() JSON
    /.meta/graphs/active/<layer>/stats   per-fop call/latency counters

Everything under /.meta is synthesized read-only at access time from
the layers below this one (walk of the live graph — no caching, the
whole point is looking at NOW); every other path passes through."""

from __future__ import annotations

import errno
import json
import time

from ..core.fops import FopError
from ..core.iatt import Iatt
from ..core.layer import FdObj, Layer, Loc, register, walk
from ..core.virtfs import (install_readonly_guards, virtual_dir_iatt,
                           virtual_file_iatt, virtual_gfid)
from ..core import gflog

META = "/.meta"


def _gfid(path: str) -> bytes:
    return virtual_gfid("meta", path)


@register("meta")
class MetaLayer(Layer):
    """Serve /.meta; wind everything else to the child."""

    # -- tree synthesis ----------------------------------------------------

    def _layers(self) -> dict[str, Layer]:
        return {l.name: l for l in walk(self.children[0])}

    def _node(self, path: str):
        """Resolve a /.meta-relative path -> ("dir", entries) or
        ("file", bytes) or None."""
        parts = [p for p in path.split("/") if p]
        if not parts:
            return "dir", ["version", "logging", "metrics",
                           "connections", "graphs"]
        if parts == ["connections"]:
            # every protocol/client transport below: connection state +
            # wire accounting (the client half of `volume status
            # clients` — same counters, read from this end)
            rows = [{"layer": l.name, **l.dump_private()}
                    for l in self._layers().values()
                    if hasattr(l, "rpc_roundtrips")]
            return "file", json.dumps(rows, indent=1,
                                      default=repr).encode()
        if parts == ["version"]:
            from .. import __version__

            return "file", json.dumps(
                {"version": __version__}, indent=1).encode()
        if parts == ["logging"]:
            return "file", "\n".join(
                gflog.recent_messages(200)).encode() + b"\n"
        if parts == ["metrics"]:
            # the unified registry's Prometheus text dump (same bytes
            # the daemon's --metrics-port endpoint serves)
            from ..core.metrics import REGISTRY

            return "file", REGISTRY.render().encode()
        if parts[0] != "graphs":
            return None
        if len(parts) == 1:
            return "dir", ["active"]
        if parts[1] != "active":
            return None
        layers = self._layers()
        if len(parts) == 2:
            return "dir", sorted(layers)
        layer = layers.get(parts[2])
        if layer is None:
            return None
        if len(parts) == 3:
            return "dir", ["type", "options", "private", "stats"]
        if len(parts) > 4:
            return None
        leaf = parts[3]
        if leaf == "type":
            return "file", (layer.type_name + "\n").encode()
        if leaf == "options":
            return "file", json.dumps(layer.opts, indent=1,
                                      default=repr).encode()
        if leaf == "private":
            return "file", json.dumps(layer.dump_private(), indent=1,
                                      default=repr).encode()
        if leaf == "stats":
            dump = layer.statedump()
            return "file", json.dumps(dump.get("stats", {}), indent=1,
                                      default=repr).encode()
        return None

    def _resolve(self, path: str):
        rel = path[len(META):]
        node = self._node(rel)
        if node is None:
            raise FopError(errno.ENOENT, path)
        return node

    def _iatt(self, path: str, node) -> Iatt:
        kind, payload = node
        if kind == "dir":
            return virtual_dir_iatt(_gfid(path))
        return virtual_file_iatt(_gfid(path), len(payload))

    @staticmethod
    def _is_meta(path: str | None) -> bool:
        return bool(path) and (path == META or
                               path.startswith(META + "/"))

    def _virt_loc(self, loc: Loc) -> bool:
        return self._is_meta(loc.path)

    def _virt_fd(self, fd: FdObj) -> bool:
        return self._is_meta(fd.path)

    # -- fops --------------------------------------------------------------

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        if not self._is_meta(loc.path):
            return await self.children[0].lookup(loc, xdata)
        node = self._resolve(loc.path)
        return self._iatt(loc.path, node), {}

    async def stat(self, loc: Loc, xdata: dict | None = None):
        if not self._is_meta(loc.path):
            return await self.children[0].stat(loc, xdata)
        return self._iatt(loc.path, self._resolve(loc.path))

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        if not self._is_meta(fd.path):
            return await self.children[0].fstat(fd, xdata)
        return self._iatt(fd.path, self._resolve(fd.path))

    async def open(self, loc: Loc, flags: int = 0,
                   xdata: dict | None = None):
        if not self._is_meta(loc.path):
            return await self.children[0].open(loc, flags, xdata)
        kind, payload = self._resolve(loc.path)
        if kind != "file":
            raise FopError(errno.EISDIR, loc.path)
        fd = FdObj(_gfid(loc.path), flags, path=loc.path)
        # pin the content for this fd: live files (stats, logging)
        # change length between chunked reads, and a regenerating
        # tail would append garbage past the first snapshot
        fd.ctx_set(self, payload)
        return fd

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        if not self._is_meta(fd.path):
            return await self.children[0].readv(fd, size, offset, xdata)
        payload = fd.ctx_get(self)
        if payload is None:  # anonymous fd: best-effort regeneration
            kind, payload = self._resolve(fd.path)
            if kind != "file":
                raise FopError(errno.EISDIR, fd.path)
        return payload[offset:offset + size]

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        if self._is_meta(fd.path):
            raise FopError(errno.EROFS, ".meta is read-only")
        return await self.children[0].writev(fd, data, offset, xdata)

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        if not self._is_meta(loc.path):
            return await self.children[0].opendir(loc, xdata)
        kind, _ = self._resolve(loc.path)
        if kind != "dir":
            raise FopError(errno.ENOTDIR, loc.path)
        return FdObj(_gfid(loc.path), path=loc.path)

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        if not self._is_meta(fd.path):
            return await self.children[0].readdir(fd, size, offset,
                                                  xdata)
        _, entries = self._resolve(fd.path)
        return [(name, None) for name in entries]

    async def readdirp(self, fd: FdObj, size: int = 0, offset: int = 0,
                       xdata: dict | None = None):
        if not self._is_meta(fd.path):
            return await self.children[0].readdirp(fd, size, offset,
                                                   xdata)
        _, entries = self._resolve(fd.path)
        out = []
        for name in entries:
            child = fd.path.rstrip("/") + "/" + name
            out.append((name, self._iatt(child, self._resolve(child))))
        return out

    async def release(self, fd: FdObj) -> None:
        if not self._is_meta(fd.path):
            await super().release(fd)

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        if self._is_meta(fd.path):
            return {}
        return await self.children[0].flush(fd, xdata)

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        if self._is_meta(loc.path):
            return {}
        return await self.children[0].getxattr(loc, name, xdata)

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Chains touching nothing under /.meta forward intact (this
        layer is pure passthrough for real files); a /.meta link makes
        the whole chain decompose so the virtual tree keeps serving."""
        from ..rpc import compound as cfop

        for _fop, args, kwargs in links:
            for a in list(args) + list((kwargs or {}).values()):
                if (isinstance(a, Loc) and self._is_meta(a.path)) or \
                        (isinstance(a, FdObj) and self._is_meta(a.path)):
                    return await cfop.decompose(self, links, xdata)
        return await self.children[0].compound(links, xdata)

    def dump_private(self) -> dict:
        return {"layers": sorted(self._layers())}


install_readonly_guards(MetaLayer, "_virt_loc", "_virt_fd",
                        ".meta is read-only")
