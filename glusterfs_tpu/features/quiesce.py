"""features/quiesce — client-side fop pause/replay.

Reference: xlators/features/quiesce (quiesce.c): during failover the
client graph can be told to hold every fop in a queue instead of
failing it; un-quiescing replays the queue in order.  Used by gfproxy
failover; here it doubles as a general pause gate (option flips via
live reconfigure, like barrier on the brick side)."""

from __future__ import annotations

import asyncio

from ..core.fops import Fop
from ..core.layer import Layer, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("quiesce")


@register("features/quiesce")
class QuiesceLayer(Layer):
    OPTIONS = (
        Option("quiesce", "bool", default="off",
               description="hold all fops until turned off again"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._gate = asyncio.Event()
        if not self.opts["quiesce"]:
            self._gate.set()
        self.queued_peak = 0
        self._waiting = 0

    def reconfigure(self, options: dict) -> None:
        super().reconfigure(options)
        if self.opts["quiesce"]:
            self._gate.clear()
        else:
            self._gate.set()  # replay: every parked fop resumes FIFO

    async def _pass(self, op_name: str, *args, **kwargs):
        if not self._gate.is_set():
            self._waiting += 1
            self.queued_peak = max(self.queued_peak, self._waiting)
            try:
                await self._gate.wait()
            finally:
                self._waiting -= 1
        return await getattr(self.children[0], op_name)(*args, **kwargs)

    def dump_private(self) -> dict:
        return {"quiesced": not self._gate.is_set(),
                "waiting": self._waiting,
                "queued_peak": self.queued_peak}


def _held(op_name: str):
    async def impl(self, *args, **kwargs):
        return await self._pass(op_name, *args, **kwargs)
    impl.__name__ = op_name
    return impl


for _f in Fop:
    setattr(QuiesceLayer, _f.value, _held(_f.value))
