"""features/barrier — quiesce mutating fops for snapshots.

Reference: xlators/features/barrier/src/barrier.c:104-256: when enabled
(by glusterd around a snapshot), the brick holds every acknowledgement-
class fop in a queue; disable (or the barrier timeout) releases them.
The snapshot then captures a store that no in-flight mutation is
touching.

Here the gate is an asyncio.Event awaited by every WRITE fop before it
winds; flipping the ``barrier`` option through live reconfigure arms or
releases it, and ``barrier-timeout`` auto-releases a forgotten barrier
(barrier.c barrier_timeout semantics).
"""

from __future__ import annotations

import asyncio
import time

from ..core.fops import Fop, WRITE_FOPS
from ..core.layer import Layer, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("barrier")

# The gated classes: everything that mutates, plus fsync — EXCEPT the
# xattrop settle ops.  The reference barriers only un-redoable acks
# (barrier.c fops table) because its snapshot device (LVM) is atomic;
# our snapshot is a store COPY, so data mutations must quiesce.  But
# the eager-window settle wave (xattrop post-op + compound unlock) must
# flow THROUGH an armed barrier: the snapshot path first fires
# contention upcalls so clients commit their delayed post-ops
# (_quiesce_client_locks), and that commit would otherwise park on the
# very barrier waiting for it.  xattrop is absent from the reference's
# barrier set too.
_GATED = (WRITE_FOPS | {Fop.FSYNC, Fop.FSYNCDIR}) \
    - {Fop.XATTROP, Fop.FXATTROP}
_GATED_NAMES = {f.value for f in _GATED}


@register("features/barrier")
class BarrierLayer(Layer):
    OPTIONS = (
        Option("barrier", "bool", default="off"),
        Option("barrier-timeout", "time", default="120"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._release: asyncio.Event | None = None
        self._armed_at = 0.0
        self.held_peak = 0
        self._held = 0
        self._inflight = 0  # gated fops past the gate, still executing
        if self.opts["barrier"]:  # volfile arrived with barrier=on
            self._arm()

    def _armed(self) -> bool:
        return self._release is not None and not self._release.is_set()

    def _arm(self) -> None:
        self._release = asyncio.Event()
        self._armed_at = time.monotonic()
        log.info(2, "%s: barrier armed (timeout %.0fs)", self.name,
                 self.opts["barrier-timeout"])

    def reconfigure(self, options: dict) -> None:
        super().reconfigure(options)
        now = self.opts["barrier"]
        if self._armed() and not now:
            self._release.set()
            log.info(1, "%s: barrier released", self.name)
        elif now and not self._armed():
            self._arm()

    async def _gate(self) -> None:
        if not self.opts["barrier"] or self._release is None:
            return
        left = self.opts["barrier-timeout"] - (time.monotonic()
                                               - self._armed_at)
        self._held += 1
        self.held_peak = max(self.held_peak, self._held)
        try:
            if left > 0:
                try:
                    await asyncio.wait_for(
                        asyncio.shield(self._release.wait()), left)
                    return
                except asyncio.TimeoutError:
                    pass
            # timeout: a forgotten barrier must not wedge the brick
            log.warning(3, "%s: barrier timed out, auto-releasing",
                        self.name)
            self.opts["barrier"] = False
            self._release.set()
        finally:
            self._held -= 1

    async def compound(self, links, xdata: dict | None = None) -> list:
        """A chain carrying any gated fop waits at the barrier ONCE as a
        unit, then forwards intact — identical quiesce semantics to its
        links arriving singly (all-or-nothing past the gate), and the
        in-flight count covers the whole chain so a snapshot still
        waits for it."""
        if any(f in _GATED_NAMES for f, _a, _k in links):
            await self._gate()
            self._inflight += 1
            try:
                return await self.children[0].compound(links, xdata)
            finally:
                self._inflight -= 1
        return await self.children[0].compound(links, xdata)

    def dump_private(self) -> dict:
        return {"barrier": self.opts["barrier"], "held": self._held,
                "held_peak": self.held_peak, "inflight": self._inflight}


def _gated_fop(fop: Fop):
    name = fop.value

    async def impl(self, *args, **kwargs):
        await self._gate()
        self._inflight += 1
        try:
            return await getattr(self.children[0], name)(*args, **kwargs)
        finally:
            self._inflight -= 1

    impl.__name__ = name
    return impl


for _f in _GATED:
    setattr(BarrierLayer, _f.value, _gated_fop(_f))

