"""features/namespace — tag requests with a namespace from the path
prefix (reference xlators/features/namespace: the first path component
hashes to a namespace id used downstream for accounting/QoS).  The tag
rides xdata as ``namespace``; per-namespace fop counts are kept for
introspection."""

from __future__ import annotations

from collections import Counter

from ..core.fops import Fop
from ..core.layer import FdObj, Layer, Loc, register


def _ns_of(path: str | None) -> str:
    if not path or path == "/":
        return "/"
    return path.lstrip("/").split("/", 1)[0]


@register("features/namespace")
class NamespaceLayer(Layer):
    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.per_ns: Counter = Counter()

    def dump_private(self) -> dict:
        return {"namespaces": dict(self.per_ns)}


def _tagging(op_name: str):
    async def impl(self, *args, **kwargs):
        from ..core.virtfs import call_with_xdata

        ns = None
        for a in args:
            if isinstance(a, (Loc, FdObj)) and a.path:
                ns = _ns_of(a.path)
                break
        if ns is None:
            return await getattr(self.children[0], op_name)(*args,
                                                            **kwargs)
        self.per_ns[ns] += 1
        return await call_with_xdata(self.children[0], op_name, args,
                                     kwargs, {"namespace": ns})
    impl.__name__ = op_name
    return impl


for _f in Fop:
    # COMPOUND stays on the inherited Layer.compound: this layer's
    # per-fop overrides make it non-transparent, so chains decompose
    # and every link gets its namespace tag — the _tagging wrapper
    # would forward the chain intact and untagged (its args are links,
    # not a Loc)
    if _f is not Fop.COMPOUND:
        setattr(NamespaceLayer, _f.value, _tagging(_f.value))
