"""features/locks — brick-side byte-range and internal locks.

Reference: xlators/features/locks (posix.c, inodelk.c, entrylk.c) with
named lock domains (common.h:61-82).  Three lock classes, same as the
reference:

* ``inodelk(domain, ...)`` — internal per-inode locks in named domains;
  the EC/AFR transaction engines serialize writers with these.
* ``entrylk(domain, loc, basename, ...)`` — internal per-dentry locks
  (directory-op serialization).
* ``lk(fd, ...)`` — POSIX advisory record locks for applications.

Locks are owner-keyed (``lk-owner`` in xdata, the frame lk_owner analog);
rd locks share, wr locks exclude, ranges conflict on overlap; blocking
requests queue FIFO on an asyncio future.
"""

from __future__ import annotations

import asyncio
import errno
from collections import defaultdict

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option


class _Lock:
    __slots__ = ("owner", "ltype", "start", "end", "client",
                 "last_notify")

    def __init__(self, owner: bytes, ltype: str, start: int, end: int):
        self.owner = owner
        self.ltype = ltype  # "rd" | "wr"
        self.start = start
        self.end = end  # exclusive; -1 = EOF (whole rest)
        # grantee's RPC identity + last contention-upcall stamp (the
        # pl_inode_lock client_uid / contention_time analogs); client is
        # stamped at grant time by LocksLayer
        self.client: bytes | None = None
        self.last_notify = 0.0

    def overlaps(self, other: "_Lock") -> bool:
        a_end = self.end if self.end >= 0 else float("inf")
        b_end = other.end if other.end >= 0 else float("inf")
        return self.start < b_end and other.start < a_end

    def conflicts(self, other: "_Lock") -> bool:
        if self.owner == other.owner:
            return False
        if self.ltype == "rd" and other.ltype == "rd":
            return False
        return self.overlaps(other)

    def to_dict(self) -> dict:
        return {"owner": self.owner.hex(), "type": self.ltype,
                "start": self.start, "end": self.end}


class _LockDomain:
    """Granted locks + FIFO waiter queue for one (gfid, domain)."""

    def __init__(self):
        self.granted: list[_Lock] = []
        self.waiters: list[tuple[_Lock, asyncio.Future]] = []

    def _grantable(self, req: _Lock) -> bool:
        return not any(g.conflicts(req) for g in self.granted)

    def try_lock(self, req: _Lock) -> bool:
        if self._grantable(req):
            self.granted.append(req)
            return True
        return False

    async def lock(self, req: _Lock) -> None:
        if self.try_lock(req):
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.waiters.append((req, fut))
        await fut

    def unlock(self, owner: bytes, start: int, end: int) -> bool:
        for i, g in enumerate(self.granted):
            if g.owner == owner and g.start == start and g.end == end:
                del self.granted[i]
                self._wake()
                return True
        return False

    def release_owner(self, owner: bytes) -> int:
        n = len(self.granted)
        self.granted = [g for g in self.granted if g.owner != owner]
        if len(self.granted) != n:
            self._wake()
        return n - len(self.granted)

    def _wake(self) -> None:
        # grant queued requests in FIFO order while compatible
        still = []
        for req, fut in self.waiters:
            if not fut.cancelled() and self._grantable(req):
                self.granted.append(req)
                fut.set_result(None)
            elif not fut.cancelled():
                still.append((req, fut))
        self.waiters = still

    def empty(self) -> bool:
        return not self.granted and not self.waiters


@register("features/locks")
class LocksLayer(Layer):
    OPTIONS = (
        Option("trace", "bool", default="off"),
        Option("lock-timeout", "time", default="30",
               description="blocking lock wait limit (0 = forever)"),
        Option("notify-contention", "bool", default="on",
               description="push an upcall to the holder of a granted "
                           "inodelk when another request blocks on it "
                           "(inodelk_contention_notify, locks "
                           "common.c:1374-1455) — EC releases its eager "
                           "window on this event instead of sitting on "
                           "the lock for the full post-op delay"),
        Option("notify-contention-delay", "time", default="5",
               description="minimum seconds between contention upcalls "
                           "for one held lock (features.locks-notify-"
                           "contention-delay)"),
        Option("monkey-unlocking", "bool", default="off",
               description="TEST TOOL (pl monkey-unlocking): ~50% of "
                           "unlocks pretend success and leak the lock, "
                           "exercising stale-lock recovery paths"),
        Option("mandatory-locking", "enum", default="off",
               values=("off", "forced"),
               description="forced: data fops conflicting with another "
                           "owner's posix lock fail EAGAIN instead of "
                           "proceeding (locks.mandatory-locking, "
                           "pl_track_io semantics)"),
    )

    def _mandatory_check(self, gfid: bytes, xdata: dict | None,
                         start: int, end: int, write: bool) -> None:
        if self.opts["mandatory-locking"] != "forced":
            return
        dom = self._posixlk.get(gfid)
        if dom is None:
            return
        from ..rpc.wire import CURRENT_CLIENT

        owner = (xdata or {}).get("lk-owner")
        me = CURRENT_CLIENT.get()
        probe = _Lock(owner or b"", "wr" if write else "rd", start, end)
        for g in dom.granted:
            if not g.overlaps(probe):
                continue
            if not write and g.ltype == "rd":
                continue
            # the HOLDER's own I/O must pass: match by lk-owner when
            # the fop carries one, else by the requesting client
            # identity (data fops usually carry no owner)
            if owner is not None and g.owner == owner:
                continue
            if owner is None and g.client == me:
                continue
            raise FopError(errno.EAGAIN,
                           "mandatory lock held by another owner")

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        self._mandatory_check(fd.gfid, xdata, offset,
                              offset + size, False)
        return await self.children[0].readv(fd, size, offset, xdata)

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        self._mandatory_check(fd.gfid, xdata, offset,
                              offset + len(data), True)
        return await self.children[0].writev(fd, data, offset, xdata)

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # (gfid, domain) -> _LockDomain for inodelks;
        # (gfid, domain, basename) for entrylks; gfid for posix lk
        self._inodelk: dict[tuple, _LockDomain] = defaultdict(_LockDomain)
        self._entrylk: dict[tuple, _LockDomain] = defaultdict(_LockDomain)
        self._posixlk: dict[bytes, _LockDomain] = defaultdict(_LockDomain)
        self._sink = None  # BrickServer's event-push callback
        self.contention_sent = 0

    def set_upcall_sink(self, sink) -> None:
        self._sink = sink

    def _contend(self, gfid: bytes, domain: str, dom: _LockDomain,
                 req: _Lock) -> None:
        """A request just blocked: tell the holders (rate-limited per
        lock) so an eager-lock client can flush and release early."""
        if self._sink is None or not self.opts["notify-contention"]:
            return
        import time as _time

        now = _time.monotonic()
        delay = self.opts["notify-contention-delay"]
        targets = set()
        for g in dom.granted:
            if g.conflicts(req) and g.client is not None and \
                    now - g.last_notify >= delay:
                g.last_notify = now
                targets.add(g.client)
        if targets:
            self.contention_sent += 1
            self._sink(sorted(targets),
                       {"event": "inodelk-contention", "gfid": gfid,
                        "domain": domain})

    def contend_held_locks(self) -> int:
        """Fire a contention upcall at every held inodelk (snapshot
        quiesce: the barrier wants clients to commit + release their
        eager windows NOW rather than on the post-op-delay timer)."""
        if self._sink is None:
            return 0
        n = 0
        for (gfid, domain), dom in list(self._inodelk.items()):
            targets = {g.client for g in dom.granted
                       if g.client is not None}
            for t in sorted(targets):
                self._sink([t], {"event": "inodelk-contention",
                                 "gfid": gfid, "domain": domain})
                n += 1
        self.contention_sent += n
        return n

    # -- helpers -----------------------------------------------------------

    async def _gfid_for(self, loc: Loc) -> bytes:
        if loc.gfid:
            return loc.gfid
        ia, _ = await self.children[0].lookup(loc)
        return ia.gfid

    @staticmethod
    def _owner(xdata: dict | None) -> bytes:
        return (xdata or {}).get("lk-owner", b"\0anon")

    async def _do(self, table: dict, key, cmd: str, req: _Lock):
        dom = table[key]
        if cmd == "unlock":
            if self.opts["monkey-unlocking"]:
                import random as _random

                if _random.random() < 0.5:
                    log_monkey = getattr(self, "monkey_dropped", 0) + 1
                    self.monkey_dropped = log_monkey
                    return {}  # lock leaks on purpose (test tool)
            if not dom.unlock(req.owner, req.start, req.end):
                raise FopError(errno.EINVAL, "no such lock")
            if dom.empty():
                table.pop(key, None)
            return {}
        from ..rpc.wire import CURRENT_CLIENT

        req.client = CURRENT_CLIENT.get()
        if cmd == "lock-nb":
            if not dom.try_lock(req):
                if table is self._inodelk:
                    self._contend(key[0], key[1], dom, req)
                raise FopError(errno.EAGAIN, "would block")
            return {}
        if cmd == "lock":
            timeout = self.opts["lock-timeout"]
            if not dom.try_lock(req):
                # blocked: nudge the holders before we park
                # (inodelk_contention_notify)
                if table is self._inodelk:
                    self._contend(key[0], key[1], dom, req)
                fut = asyncio.get_running_loop().create_future()
                dom.waiters.append((req, fut))
                try:
                    await asyncio.wait_for(fut, timeout or None)
                except asyncio.TimeoutError:
                    raise FopError(errno.ETIMEDOUT,
                                   "lock wait timed out") from None
            return {}
        raise FopError(errno.EINVAL, f"bad lock cmd {cmd!r}")

    # -- fops --------------------------------------------------------------

    async def inodelk(self, domain: str, loc: Loc, cmd: str,
                      ltype: str = "wr", start: int = 0, end: int = -1,
                      xdata: dict | None = None):
        gfid = await self._gfid_for(loc)
        ret = await self._do(self._inodelk, (gfid, domain), cmd,
                             _Lock(self._owner(xdata), ltype, start, end))
        if cmd in ("lock", "lock-nb") and (xdata or {}).get("get-xattrs"):
            # lock-and-fetch: return the inode's xattrs with the grant,
            # saving the caller a separate metadata round trip (the
            # xdata-piggyback idiom the reference uses on lookups).
            # None on failure — callers must never mistake a failed
            # fetch for an inode with no xattrs
            try:
                return await self.children[0].getxattr(loc, None)
            except FopError:
                return None
        return ret

    async def finodelk(self, domain: str, fd: FdObj, cmd: str,
                       ltype: str = "wr", start: int = 0, end: int = -1,
                       xdata: dict | None = None):
        return await self._do(self._inodelk, (fd.gfid, domain), cmd,
                              _Lock(self._owner(xdata), ltype, start, end))

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        """Compound lock-on-create: a ``lock-inodelk`` payload takes the
        caller's transaction lock right after the create commits — the
        mirror of xattrop's compound unlock, saving EC's eager window
        its opening lock wave on the create-first write path.  Callers
        only attach it to O_EXCL creates: the file (and its fresh gfid)
        is born with this fop, so the non-blocking grant cannot
        conflict with anyone."""
        grant = (xdata or {}).get("lock-inodelk")
        if grant:
            xdata = {k: v for k, v in xdata.items()
                     if k != "lock-inodelk"}
        ret = await self.children[0].create(loc, flags, mode, xdata)
        if grant:
            domain, ltype, start, end, owner = grant
            fd = ret[0] if isinstance(ret, tuple) else ret
            await self._do(self._inodelk, (fd.gfid, domain), "lock-nb",
                           _Lock(owner, ltype, start, end))
        return ret

    async def xattrop(self, loc: Loc, op: str, xattrs: dict,
                      xdata: dict | None = None):
        """Compound post-op: an ``unlock-inodelk`` payload releases the
        caller's transaction lock right after the xattrop commits —
        clients fold the window-close unlock wave into the post-op wave
        (ordering preserved: counters land, then the lock drops)."""
        unlock = (xdata or {}).get("unlock-inodelk")
        if unlock:
            xdata = {k: v for k, v in xdata.items()
                     if k != "unlock-inodelk"}
        out = await self.children[0].xattrop(loc, op, xattrs, xdata)
        if unlock:
            domain, ltype, start, end, owner = unlock
            try:
                await self.inodelk(domain, loc, "unlock", ltype,
                                   start, end, {"lk-owner": owner})
            except FopError:
                pass  # already gone (restarted brick): nothing to drop
        return out

    async def entrylk(self, domain: str, loc: Loc, basename: str,
                      cmd: str, ltype: str = "wr",
                      xdata: dict | None = None):
        gfid = await self._gfid_for(loc)
        return await self._do(self._entrylk, (gfid, domain, basename), cmd,
                              _Lock(self._owner(xdata), ltype, 0, -1))

    async def fentrylk(self, domain: str, fd: FdObj, basename: str,
                       cmd: str, ltype: str = "wr",
                       xdata: dict | None = None):
        return await self._do(self._entrylk, (fd.gfid, domain, basename),
                              cmd, _Lock(self._owner(xdata), ltype, 0, -1))

    async def lk(self, fd: FdObj, cmd: str, flock: dict,
                 xdata: dict | None = None):
        """POSIX record locks: flock = {type: rd|wr|unlck, start, len}."""
        owner = self._owner(xdata)
        start = flock.get("start", 0)
        length = flock.get("len", 0)
        end = -1 if length == 0 else start + length
        ltype = flock.get("type", "wr")
        dom = self._posixlk[fd.gfid]
        if cmd == "getlk":
            probe = _Lock(owner, ltype, start, end)
            for g in dom.granted:
                if g.conflicts(probe):
                    return {"type": g.ltype, "start": g.start,
                            "end": g.end, "owner": g.owner.hex()}
            return {"type": "unlck"}
        if ltype == "unlck":
            dom.release_owner(owner)
            if dom.empty():
                self._posixlk.pop(fd.gfid, None)
            return {}
        mapped = {"setlk": "lock-nb", "setlkw": "lock"}.get(cmd)
        if mapped is None:
            raise FopError(errno.EINVAL, f"bad lk cmd {cmd!r}")
        return await self._do(self._posixlk, fd.gfid, mapped,
                              _Lock(owner, ltype, start, end))

    async def getactivelk(self, loc: Loc, xdata: dict | None = None):
        gfid = await self._gfid_for(loc)
        out = []
        for (g, dom_name), dom in self._inodelk.items():
            if g == gfid:
                out.extend({**lk.to_dict(), "domain": dom_name}
                           for lk in dom.granted)
        return out

    def release_client(self, owner: bytes) -> int:
        """Drop every lock held by a disconnected client (the reference
        cleans locks on client disconnect via client_t)."""
        n = 0
        for table in (self._inodelk, self._entrylk, self._posixlk):
            for key in list(table):
                n += table[key].release_owner(owner)
                if table[key].empty():
                    table.pop(key, None)
        return n

    def dump_private(self) -> dict:
        return {
            "inodelk_domains": len(self._inodelk),
            "entrylk_domains": len(self._entrylk),
            "posixlk_inodes": len(self._posixlk),
            "granted": sum(len(d.granted) for d in self._inodelk.values()),
            "waiting": sum(len(d.waiters) for d in self._inodelk.values()),
        }
