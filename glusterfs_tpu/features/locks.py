"""features/locks — brick-side byte-range and internal locks.

Reference: xlators/features/locks (posix.c, inodelk.c, entrylk.c) with
named lock domains (common.h:61-82).  Three lock classes, same as the
reference:

* ``inodelk(domain, ...)`` — internal per-inode locks in named domains;
  the EC/AFR transaction engines serialize writers with these.
* ``entrylk(domain, loc, basename, ...)`` — internal per-dentry locks
  (directory-op serialization).
* ``lk(fd, ...)`` — POSIX advisory record locks for applications.

Locks are owner-keyed (``lk-owner`` in xdata, the frame lk_owner analog);
rd locks share, wr locks exclude, ranges conflict on overlap; blocking
requests queue FIFO on an asyncio future.
"""

from __future__ import annotations

import asyncio
import errno
import time as _time
from collections import defaultdict

from ..core.events import gf_event
from ..core.fops import FopError
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import metrics as _metrics

#: live locks layers, scraped by the unified registry (weak: retired
#: graphs age out with the GC).  The revocation counter and the wedge
#: gauges hang off one population.
_LIVE_LOCKS_LAYERS = _metrics.REGISTRY.register_objects(
    "gftpu_locks_revoked_total", "counter",
    "granted locks forcibly revoked, by trigger (age = holder older "
    "than features.locks-revocation-secs with waiters queued, "
    "max-blocked = blocked queue over features.locks-revocation-"
    "max-blocked, clear-locks = operator `volume clear-locks`)",
    lambda l: [({"layer": l.name, "reason": r}, v)
               for r, v in l.revoked_counts.items()])
_metrics.REGISTRY.register_objects(
    "gftpu_locks_blocked", "gauge",
    "lock requests currently parked in FIFO waiter queues, per table",
    lambda l: [({"layer": l.name, "kind": k}, v)
               for k, v in l._blocked_counts().items()],
    live=_LIVE_LOCKS_LAYERS)


class _Lock:
    __slots__ = ("owner", "ltype", "start", "end", "client",
                 "last_notify", "granted_at")

    def __init__(self, owner: bytes, ltype: str, start: int, end: int):
        self.owner = owner
        self.ltype = ltype  # "rd" | "wr"
        self.start = start
        self.end = end  # exclusive; -1 = EOF (whole rest)
        # grantee's RPC identity + last contention-upcall stamp (the
        # pl_inode_lock client_uid / contention_time analogs); client is
        # stamped at grant time by LocksLayer
        self.client: bytes | None = None
        self.last_notify = 0.0
        # monotonic grant stamp: the revocation monitor ages holders
        # from this (pl_inode_lock granted_time)
        self.granted_at = 0.0

    def overlaps(self, other: "_Lock") -> bool:
        a_end = self.end if self.end >= 0 else float("inf")
        b_end = other.end if other.end >= 0 else float("inf")
        return self.start < b_end and other.start < a_end

    def conflicts(self, other: "_Lock") -> bool:
        if self.owner == other.owner:
            return False
        if self.ltype == "rd" and other.ltype == "rd":
            return False
        return self.overlaps(other)

    def to_dict(self) -> dict:
        return {"owner": self.owner.hex(), "type": self.ltype,
                "start": self.start, "end": self.end}


class _LockDomain:
    """Granted locks + FIFO waiter queue for one (gfid, domain).
    Waiter entries are ``(req, fut, since)`` — the monotonic park stamp
    feeds the wedge view and the revocation monitor."""

    def __init__(self):
        self.granted: list[_Lock] = []
        self.waiters: list[tuple[_Lock, asyncio.Future, float]] = []

    def _grantable(self, req: _Lock) -> bool:
        return not any(g.conflicts(req) for g in self.granted)

    def _grant(self, req: _Lock) -> None:
        req.granted_at = _time.monotonic()
        self.granted.append(req)

    def try_lock(self, req: _Lock) -> bool:
        if self._grantable(req):
            self._grant(req)
            return True
        return False

    async def lock(self, req: _Lock) -> None:
        if self.try_lock(req):
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self.waiters.append((req, fut, _time.monotonic()))
        await fut

    def unlock(self, owner: bytes, start: int, end: int) -> bool:
        for i, g in enumerate(self.granted):
            if g.owner == owner and g.start == start and g.end == end:
                del self.granted[i]
                self._wake()
                return True
        return False

    def release_owner(self, owner: bytes) -> int:
        n = len(self.granted)
        self.granted = [g for g in self.granted if g.owner != owner]
        if len(self.granted) != n:
            self._wake()
        return n - len(self.granted)

    def release_matching(self, pred) -> int:
        """Drop granted locks matching ``pred(lock)`` and evict matching
        waiters (their futures fail ENOTCONN so in-process callers
        unblock), then grant whoever became compatible."""
        n = len(self.granted)
        self.granted = [g for g in self.granted if not pred(g)]
        gone = n - len(self.granted)
        still = []
        for req, fut, since in self.waiters:
            if pred(req):
                if not fut.done():
                    fut.set_exception(FopError(
                        errno.ENOTCONN, "lock waiter's client went away"))
            else:
                still.append((req, fut, since))
        self.waiters = still
        if gone:
            self._wake()
        return gone

    def _wake(self) -> None:
        # grant queued requests in FIFO order while compatible
        still = []
        for req, fut, since in self.waiters:
            if not fut.cancelled() and self._grantable(req):
                self._grant(req)
                fut.set_result(None)
            elif not fut.cancelled():
                still.append((req, fut, since))
        self.waiters = still

    def oldest_holder_age(self) -> float:
        if not self.granted:
            return 0.0
        now = _time.monotonic()
        return max(now - g.granted_at for g in self.granted)

    def oldest_waiter_age(self) -> float:
        if not self.waiters:
            return 0.0
        now = _time.monotonic()
        return max(now - since for _r, _f, since in self.waiters)

    def empty(self) -> bool:
        return not self.granted and not self.waiters


@register("features/locks")
class LocksLayer(Layer):
    OPTIONS = (
        Option("trace", "bool", default="off"),
        Option("lock-timeout", "time", default="30",
               description="blocking lock wait limit (0 = forever)"),
        Option("notify-contention", "bool", default="on",
               description="push an upcall to the holder of a granted "
                           "inodelk when another request blocks on it "
                           "(inodelk_contention_notify, locks "
                           "common.c:1374-1455) — EC releases its eager "
                           "window on this event instead of sitting on "
                           "the lock for the full post-op delay"),
        Option("notify-contention-delay", "time", default="5",
               description="minimum seconds between contention upcalls "
                           "for one held lock (features.locks-notify-"
                           "contention-delay)"),
        Option("monkey-unlocking", "bool", default="off",
               description="TEST TOOL (pl monkey-unlocking): ~50% of "
                           "unlocks pretend success and leak the lock, "
                           "exercising stale-lock recovery paths"),
        Option("revocation-secs", "time", default="0",
               description="forced revocation of wedged holders "
                           "(features.locks-revocation-secs, reference "
                           "entrylk.c:129-173 + the inodelk twin): "
                           "while requests queue behind a granted lock "
                           "older than this, the monitor revokes the "
                           "domain's holders, drains the FIFO waiter "
                           "queue, and the revoked owner's next lock "
                           "fop gets EAGAIN with a 'lock-revoked' "
                           "notice in the error xdata.  0 = never "
                           "revoke (the reference default)"),
        Option("revocation-clear-all", "bool", default="off",
               description="on revocation also CLEAR the blocked queue "
                           "(features.locks-revocation-clear-all): "
                           "waiters fail EAGAIN instead of being "
                           "granted — the domain starts from empty"),
        Option("revocation-max-blocked", "int", default=0, min=0,
               description="revoke a domain's holders as soon as its "
                           "blocked queue exceeds this many waiters, "
                           "regardless of holder age (features.locks-"
                           "revocation-max-blocked); 0 = no queue "
                           "trigger"),
        Option("mandatory-locking", "enum", default="off",
               values=("off", "forced"),
               description="forced: data fops conflicting with another "
                           "owner's posix lock fail EAGAIN instead of "
                           "proceeding (locks.mandatory-locking, "
                           "pl_track_io semantics)"),
    )

    def _mandatory_check(self, gfid: bytes, xdata: dict | None,
                         start: int, end: int, write: bool) -> None:
        if self.opts["mandatory-locking"] != "forced":
            return
        dom = self._posixlk.get(gfid)
        if dom is None:
            return
        from ..rpc.wire import CURRENT_CLIENT

        owner = (xdata or {}).get("lk-owner")
        me = CURRENT_CLIENT.get()
        probe = _Lock(owner or b"", "wr" if write else "rd", start, end)
        for g in dom.granted:
            if not g.overlaps(probe):
                continue
            if not write and g.ltype == "rd":
                continue
            # the HOLDER's own I/O must pass: match by lk-owner when
            # the fop carries one, else by the requesting client
            # identity (data fops usually carry no owner)
            if owner is not None and g.owner == owner:
                continue
            if owner is None and g.client == me:
                continue
            raise FopError(errno.EAGAIN,
                           "mandatory lock held by another owner")

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        self._mandatory_check(fd.gfid, xdata, offset,
                              offset + size, False)
        return await self.children[0].readv(fd, size, offset, xdata)

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        self._mandatory_check(fd.gfid, xdata, offset,
                              offset + len(data), True)
        return await self.children[0].writev(fd, data, offset, xdata)

    async def xorv(self, fd: FdObj, data, offset: int,
                   xdata: dict | None = None):
        # the parity-delta apply is a write: mandatory locking must
        # fence it exactly like writev (same byte range)
        self._mandatory_check(fd.gfid, xdata, offset,
                              offset + len(data), True)
        return await self.children[0].xorv(fd, data, offset, xdata)

    # -- the rest of the content-mutating vocabulary (graft-lint GL01
    # fence parity: xorv above was itself an after-the-fact fence;
    # these siblings mutate byte ranges the same way) ----------------------

    _EOF = 1 << 62  # "to end of file" range bound (F_WRLCK l_len=0)

    async def truncate(self, loc, size: int, xdata: dict | None = None):
        # every byte from the new size to EOF changes (both directions)
        self._mandatory_check(loc.gfid, xdata, size, self._EOF, True)
        return await self.children[0].truncate(loc, size, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        self._mandatory_check(fd.gfid, xdata, size, self._EOF, True)
        return await self.children[0].ftruncate(fd, size, xdata)

    async def fallocate(self, fd: FdObj, mode: int, offset: int,
                        length: int, xdata: dict | None = None):
        self._mandatory_check(fd.gfid, xdata, offset, offset + length,
                              True)
        return await self.children[0].fallocate(fd, mode, offset,
                                                length, xdata)

    async def discard(self, fd: FdObj, offset: int, length: int,
                      xdata: dict | None = None):
        self._mandatory_check(fd.gfid, xdata, offset, offset + length,
                              True)
        return await self.children[0].discard(fd, offset, length, xdata)

    async def zerofill(self, fd: FdObj, offset: int, length: int,
                       xdata: dict | None = None):
        self._mandatory_check(fd.gfid, xdata, offset, offset + length,
                              True)
        return await self.children[0].zerofill(fd, offset, length,
                                               xdata)

    async def put(self, loc, data, *args, **kwargs):
        # whole-object body write (posix serves it as create+writev
        # BELOW this layer — the range check must happen here)
        self._mandatory_check(loc.gfid, kwargs.get("xdata"), 0,
                              self._EOF, True)
        return await self.children[0].put(loc, data, *args, **kwargs)

    async def copy_file_range(self, fd_in: FdObj, off_in: int,
                              fd_out: FdObj, off_out: int, length: int,
                              xdata: dict | None = None):
        # source half is a read, destination half a write — both fence
        self._mandatory_check(fd_in.gfid, xdata, off_in,
                              off_in + length, False)
        self._mandatory_check(fd_out.gfid, xdata, off_out,
                              off_out + length, True)
        return await self.children[0].copy_file_range(
            fd_in, off_in, fd_out, off_out, length, xdata)

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # (gfid, domain) -> _LockDomain for inodelks;
        # (gfid, domain, basename) for entrylks; gfid for posix lk
        self._inodelk: dict[tuple, _LockDomain] = defaultdict(_LockDomain)
        self._entrylk: dict[tuple, _LockDomain] = defaultdict(_LockDomain)
        self._posixlk: dict[bytes, _LockDomain] = defaultdict(_LockDomain)
        self._sink = None  # BrickServer's event-push callback
        self.contention_sent = 0
        # revocation plane (features.locks-revocation-*): per-trigger
        # revoked-lock counts (the gftpu_locks_revoked_total family)
        # and the pending owner notices — a revoked owner's NEXT lock
        # fop gets EAGAIN with the notice in the error xdata
        self.revoked_counts: dict[str, int] = {}
        self._revocation_notices: dict[bytes, dict] = {}
        self._monitor_task: asyncio.Task | None = None
        _LIVE_LOCKS_LAYERS.add(self)

    async def init(self):
        await super().init()
        # revocation monitor: age-triggered revocation must fire while
        # every party is parked (no new request would ever re-check), so
        # a ticker owns the deadline.  Started unconditionally — the
        # options are read per-tick so `volume set` arms it live
        self._monitor_task = asyncio.create_task(self._revocation_loop())

    async def fini(self):
        if self._monitor_task is not None:
            self._monitor_task.cancel()
            try:
                await self._monitor_task
            except (asyncio.CancelledError, Exception):
                pass
            self._monitor_task = None
        await super().fini()

    def set_upcall_sink(self, sink) -> None:
        self._sink = sink

    def _contend(self, gfid: bytes, domain: str, dom: _LockDomain,
                 req: _Lock) -> None:
        """A request just blocked: tell the holders (rate-limited per
        lock) so an eager-lock client can flush and release early."""
        if self._sink is None or not self.opts["notify-contention"]:
            return
        import time as _time

        now = _time.monotonic()
        delay = self.opts["notify-contention-delay"]
        targets = set()
        for g in dom.granted:
            if g.conflicts(req) and g.client is not None and \
                    now - g.last_notify >= delay:
                g.last_notify = now
                targets.add(g.client)
        if targets:
            self.contention_sent += 1
            self._sink(sorted(targets),
                       {"event": "inodelk-contention", "gfid": gfid,
                        "domain": domain})

    def contend_held_locks(self) -> int:
        """Fire a contention upcall at every held inodelk (snapshot
        quiesce: the barrier wants clients to commit + release their
        eager windows NOW rather than on the post-op-delay timer)."""
        if self._sink is None:
            return 0
        n = 0
        for (gfid, domain), dom in list(self._inodelk.items()):
            targets = {g.client for g in dom.granted
                       if g.client is not None}
            for t in sorted(targets):
                self._sink([t], {"event": "inodelk-contention",
                                 "gfid": gfid, "domain": domain})
                n += 1
        self.contention_sent += n
        return n

    def inodelk_holders(self, gfid: bytes,
                        but_not: bytes | None = None) -> set[bytes]:
        """Clients (other than ``but_not``) holding an inodelk on the
        gfid in ANY domain — the leases layer's grant path asks this to
        find open eager windows worth settling."""
        holders: set[bytes] = set()
        for (g, _domain), dom in self._inodelk.items():
            if g != gfid:
                continue
            holders.update(x.client for x in dom.granted
                           if x.client is not None
                           and x.client != but_not)
        return holders

    def contend_gfid(self, gfid: bytes,
                     but_not: bytes | None = None) -> int:
        """Fire a contention upcall at every inodelk holder on one gfid
        (a lease grant is a reader's registered interest: the holder's
        eager window should commit its delayed post-op NOW).  Same
        rate limit as _contend so a grant storm cannot flood a holder."""
        if self._sink is None:
            return 0
        import time as _time

        now = _time.monotonic()
        delay = self.opts["notify-contention-delay"]
        n = 0
        for (g, domain), dom in list(self._inodelk.items()):
            if g != gfid:
                continue
            targets = set()
            for x in dom.granted:
                if x.client is not None and x.client != but_not and \
                        now - x.last_notify >= delay:
                    x.last_notify = now
                    targets.add(x.client)
            for t in sorted(targets):
                self._sink([t], {"event": "inodelk-contention",
                                 "gfid": gfid, "domain": domain})
                n += 1
        self.contention_sent += n
        return n

    # -- forced revocation (features.locks-revocation-*; the reference's
    # entrylk.c:129-173 revocation machinery + the inodelk twin) ----------

    _TABLE_KINDS = ("inodelk", "entrylk", "posix")

    def _tables(self):
        return zip(self._TABLE_KINDS,
                   (self._inodelk, self._entrylk, self._posixlk))

    def _blocked_counts(self) -> dict[str, int]:
        return {kind: sum(len(d.waiters) for d in table.values())
                for kind, table in self._tables()}

    @staticmethod
    def _describe_key(kind: str, key) -> dict:
        if kind == "inodelk":
            return {"gfid": key[0].hex(), "domain": key[1]}
        if kind == "entrylk":
            return {"gfid": key[0].hex(), "domain": key[1],
                    "basename": key[2]}
        return {"gfid": key.hex() if isinstance(key, bytes) else str(key)}

    def _note_revoked(self, kind: str, key, lock: _Lock,
                      reason: str) -> None:
        """Remember the revocation for the owner's next lock fop (the
        EAGAIN + xdata notice).  Bounded FIFO: a dead owner that never
        returns must not pin entries forever."""
        note = {"reason": reason, "kind": kind, "ltype": lock.ltype,
                "start": lock.start, "end": lock.end,
                "held_secs": round(_time.monotonic() - lock.granted_at, 3),
                **self._describe_key(kind, key)}
        self._revocation_notices[lock.owner] = note
        while len(self._revocation_notices) > 512:
            self._revocation_notices.pop(
                next(iter(self._revocation_notices)))

    def _revoke_domain(self, kind: str, key, dom: _LockDomain,
                       reason: str, what: str = "all") -> int:
        """Revoke one domain: drop its granted locks (``what`` in
        granted/all), optionally flush its blocked queue (clear-all or
        ``what`` in blocked/all for the operator path), then drain the
        FIFO waiter queue through the usual grant path.  Returns how
        many locks were cleared (granted + flushed waiters)."""
        cleared = 0
        if what in ("granted", "all") and dom.granted:
            for g in dom.granted:
                self._note_revoked(kind, key, g, reason)
            cleared += len(dom.granted)
            dom.granted.clear()
        flush_blocked = what in ("blocked", "all") or \
            (reason != "clear-locks" and self.opts["revocation-clear-all"])
        if flush_blocked and dom.waiters:
            for _req, fut, _since in dom.waiters:
                if not fut.done():
                    fut.set_exception(FopError(
                        errno.EAGAIN, "blocked lock cleared by "
                                      "revocation",
                        xdata={"lock-revoked": {
                            "reason": reason, "kind": kind,
                            **self._describe_key(kind, key)}}))
            cleared += len(dom.waiters)
            dom.waiters.clear()
        # grant whoever is compatible now (the queue DRAIN the
        # revocation exists for)
        dom._wake()
        if cleared:
            self.revoked_counts[reason] = \
                self.revoked_counts.get(reason, 0) + cleared
            gf_event("LOCK_REVOKED", layer=self.name, kind=kind,
                     reason=reason, cleared=cleared,
                     waiters=len(dom.waiters),
                     **self._describe_key(kind, key))
        return cleared

    def _maybe_revoke(self, kind: str, key, dom: _LockDomain) -> None:
        """Apply the two automatic triggers to one domain.  Called from
        the monitor tick and at waiter-park time (the max-blocked
        trigger must fire on the block that crosses the line, not a
        second later)."""
        if not dom.waiters or not dom.granted:
            return
        maxb = int(self.opts["revocation-max-blocked"] or 0)
        if maxb and len(dom.waiters) > maxb:
            self._revoke_domain(kind, key, dom, "max-blocked", "granted")
            return
        secs = float(self.opts["revocation-secs"] or 0)
        if secs and dom.oldest_holder_age() >= secs:
            self._revoke_domain(kind, key, dom, "age", "granted")

    async def _revocation_loop(self) -> None:
        """The revocation monitor: scans domains carrying waiters on a
        tick scaled to the configured deadline (options re-read per
        tick, so ``volume set`` arms/disarms live)."""
        try:
            while True:
                secs = float(self.opts["revocation-secs"] or 0)
                tick = max(0.05, min(1.0, secs / 4)) if secs else 1.0
                await asyncio.sleep(tick)
                if not secs and not self.opts["revocation-max-blocked"]:
                    continue
                for kind, table in self._tables():
                    for key, dom in list(table.items()):
                        self._maybe_revoke(kind, key, dom)
                        if dom.empty():
                            table.pop(key, None)
        except asyncio.CancelledError:
            pass

    def _ensure_monitor(self) -> None:
        """(Re)start the monitor on the CURRENT loop: test harnesses
        activate graphs on one short-lived loop and run fops on another,
        which strands the init-time task on a dead loop."""
        t = self._monitor_task
        try:
            if t is not None and not t.done() and \
                    t.get_loop() is asyncio.get_running_loop():
                return
        except RuntimeError:
            return  # no running loop: nothing to park on either
        self._monitor_task = asyncio.create_task(self._revocation_loop())

    async def clear_locks(self, path: str, kind: str = "all",
                          xdata: dict | None = None) -> dict:
        """Operator-forced clearing (`volume clear-locks`, the
        reference's clear-locks command riding the same machinery):
        ``kind`` in blocked/granted/all; clears every lock table's
        domains for the path's gfid and drains the queues."""
        if kind not in ("blocked", "granted", "all"):
            raise FopError(errno.EINVAL,
                           f"clear-locks kind {kind!r} not one of "
                           "blocked/granted/all")
        gfid = await self._gfid_for(Loc(path))
        out = {"path": path, "kind": kind, "cleared": {}, "total": 0}
        for tkind, table in self._tables():
            n = 0
            for key, dom in list(table.items()):
                kg = key[0] if isinstance(key, tuple) else key
                if kg != gfid:
                    continue
                n += self._revoke_domain(tkind, key, dom,
                                         "clear-locks", kind)
                if dom.empty():
                    table.pop(key, None)
            if n:
                out["cleared"][tkind] = n
                out["total"] += n
        return out

    def _consume_notice(self, owner: bytes) -> None:
        """EAGAIN + notice for a revoked owner's next lock fop: the
        holder learns its lock is gone the moment it comes back for
        one (pairs with client.strict-locks, which already fails the
        lock-protected I/O path on handle loss)."""
        note = self._revocation_notices.pop(owner, None)
        if note is not None:
            raise FopError(errno.EAGAIN,
                           "lock revoked (features.locks-revocation)",
                           xdata={"lock-revoked": note})

    # -- helpers -----------------------------------------------------------

    async def _gfid_for(self, loc: Loc) -> bytes:
        if loc.gfid:
            return loc.gfid
        ia, _ = await self.children[0].lookup(loc)
        return ia.gfid

    @staticmethod
    def _owner(xdata: dict | None) -> bytes:
        return (xdata or {}).get("lk-owner", b"\0anon")

    async def _do(self, table: dict, key, cmd: str, req: _Lock):
        dom = table[key]
        if cmd == "unlock":
            if self.opts["monkey-unlocking"]:
                import random as _random

                if _random.random() < 0.5:
                    log_monkey = getattr(self, "monkey_dropped", 0) + 1
                    self.monkey_dropped = log_monkey
                    return {}  # lock leaks on purpose (test tool)
            if not dom.unlock(req.owner, req.start, req.end):
                raise FopError(errno.EINVAL, "no such lock")
            if dom.empty():
                table.pop(key, None)
            return {}
        from ..rpc.wire import CURRENT_CLIENT

        req.client = CURRENT_CLIENT.get()
        kind = next(k for k, t in self._tables() if t is table)
        # a revoked owner's next lock fop carries the notice (EAGAIN)
        self._consume_notice(req.owner)
        if cmd == "lock-nb":
            if not dom.try_lock(req):
                if table is self._inodelk:
                    self._contend(key[0], key[1], dom, req)
                raise FopError(errno.EAGAIN, "would block")
            return {}
        if cmd == "lock":
            timeout = self.opts["lock-timeout"]
            if not dom.try_lock(req):
                # blocked: nudge the holders before we park
                # (inodelk_contention_notify)
                if table is self._inodelk:
                    self._contend(key[0], key[1], dom, req)
                fut = asyncio.get_running_loop().create_future()
                dom.waiters.append((req, fut, _time.monotonic()))
                # the park that crosses revocation-max-blocked (or meets
                # an already-aged holder) fires the revocation NOW; the
                # monitor covers deadlines that pass while parked
                self._ensure_monitor()
                self._maybe_revoke(kind, key, dom)
                try:
                    await asyncio.wait_for(fut, timeout or None)
                except asyncio.TimeoutError:
                    # drop our (cancelled) waiter entry: the wedge
                    # gauges and max-blocked trigger must not count it
                    dom.waiters = [w for w in dom.waiters
                                   if w[1] is not fut]
                    raise FopError(errno.ETIMEDOUT,
                                   "lock wait timed out") from None
            return {}
        raise FopError(errno.EINVAL, f"bad lock cmd {cmd!r}")

    # -- fops --------------------------------------------------------------

    async def inodelk(self, domain: str, loc: Loc, cmd: str,
                      ltype: str = "wr", start: int = 0, end: int = -1,
                      xdata: dict | None = None):
        gfid = await self._gfid_for(loc)
        ret = await self._do(self._inodelk, (gfid, domain), cmd,
                             _Lock(self._owner(xdata), ltype, start, end))
        if cmd in ("lock", "lock-nb") and (xdata or {}).get("get-xattrs"):
            # lock-and-fetch: return the inode's xattrs with the grant,
            # saving the caller a separate metadata round trip (the
            # xdata-piggyback idiom the reference uses on lookups).
            # None on failure — callers must never mistake a failed
            # fetch for an inode with no xattrs
            try:
                return await self.children[0].getxattr(loc, None)
            except FopError:
                return None
        return ret

    async def finodelk(self, domain: str, fd: FdObj, cmd: str,
                       ltype: str = "wr", start: int = 0, end: int = -1,
                       xdata: dict | None = None):
        return await self._do(self._inodelk, (fd.gfid, domain), cmd,
                              _Lock(self._owner(xdata), ltype, start, end))

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        """Compound lock-on-create: a ``lock-inodelk`` payload takes the
        caller's transaction lock right after the create commits — the
        mirror of xattrop's compound unlock, saving EC's eager window
        its opening lock wave on the create-first write path.  Callers
        only attach it to O_EXCL creates: the file (and its fresh gfid)
        is born with this fop, so the non-blocking grant cannot
        conflict with anyone."""
        grant = (xdata or {}).get("lock-inodelk")
        if grant:
            xdata = {k: v for k, v in xdata.items()
                     if k != "lock-inodelk"}
        ret = await self.children[0].create(loc, flags, mode, xdata)
        if grant:
            domain, ltype, start, end, owner = grant
            fd = ret[0] if isinstance(ret, tuple) else ret
            await self._do(self._inodelk, (fd.gfid, domain), "lock-nb",
                           _Lock(owner, ltype, start, end))
        return ret

    async def xattrop(self, loc: Loc, op: str, xattrs: dict,
                      xdata: dict | None = None):
        """Compound post-op: an ``unlock-inodelk`` payload releases the
        caller's transaction lock right after the xattrop commits —
        clients fold the window-close unlock wave into the post-op wave
        (ordering preserved: counters land, then the lock drops)."""
        unlock = (xdata or {}).get("unlock-inodelk")
        if unlock:
            xdata = {k: v for k, v in xdata.items()
                     if k != "unlock-inodelk"}
        out = await self.children[0].xattrop(loc, op, xattrs, xdata)
        if unlock:
            domain, ltype, start, end, owner = unlock
            try:
                await self.inodelk(domain, loc, "unlock", ltype,
                                   start, end, {"lk-owner": owner})
            except FopError:
                pass  # already gone (restarted brick): nothing to drop
        return out

    async def entrylk(self, domain: str, loc: Loc, basename: str,
                      cmd: str, ltype: str = "wr",
                      xdata: dict | None = None):
        gfid = await self._gfid_for(loc)
        return await self._do(self._entrylk, (gfid, domain, basename), cmd,
                              _Lock(self._owner(xdata), ltype, 0, -1))

    async def fentrylk(self, domain: str, fd: FdObj, basename: str,
                       cmd: str, ltype: str = "wr",
                       xdata: dict | None = None):
        return await self._do(self._entrylk, (fd.gfid, domain, basename),
                              cmd, _Lock(self._owner(xdata), ltype, 0, -1))

    async def lk(self, fd: FdObj, cmd: str, flock: dict,
                 xdata: dict | None = None):
        """POSIX record locks: flock = {type: rd|wr|unlck, start, len}."""
        owner = self._owner(xdata)
        start = flock.get("start", 0)
        length = flock.get("len", 0)
        end = -1 if length == 0 else start + length
        ltype = flock.get("type", "wr")
        dom = self._posixlk[fd.gfid]
        if cmd == "getlk":
            probe = _Lock(owner, ltype, start, end)
            for g in dom.granted:
                if g.conflicts(probe):
                    return {"type": g.ltype, "start": g.start,
                            "end": g.end, "owner": g.owner.hex()}
            return {"type": "unlck"}
        if ltype == "unlck":
            dom.release_owner(owner)
            if dom.empty():
                self._posixlk.pop(fd.gfid, None)
            return {}
        mapped = {"setlk": "lock-nb", "setlkw": "lock"}.get(cmd)
        if mapped is None:
            raise FopError(errno.EINVAL, f"bad lk cmd {cmd!r}")
        return await self._do(self._posixlk, fd.gfid, mapped,
                              _Lock(owner, ltype, start, end))

    async def getactivelk(self, loc: Loc, xdata: dict | None = None):
        gfid = await self._gfid_for(loc)
        out = []
        for (g, dom_name), dom in self._inodelk.items():
            if g == gfid:
                out.extend({**lk.to_dict(), "domain": dom_name}
                           for lk in dom.granted)
        return out

    def release_client(self, owner: bytes) -> int:
        """Drop every lock held by a disconnected client (the reference
        cleans locks on client disconnect via client_t) and drain the
        freed queues WITHOUT waiting for revocation-secs.

        ``owner`` is either a bare lk-owner (in-process callers) or a
        connection identity: the wire scopes owners to
        ``identity + b"/" + lk-owner`` (protocol/server._scope_owner),
        so match the exact owner, the scoped prefix, AND the grant-time
        client identity — an identity-only match is what reaps wire
        clients' locks at all.  The dead client's own parked waiters
        are evicted too (nobody will ever collect their grant)."""
        prefix = owner + b"/"

        def dead(lk: _Lock) -> bool:
            return lk.owner == owner or lk.owner.startswith(prefix) or \
                lk.client == owner

        n = 0
        for _kind, table in self._tables():
            for key in list(table):
                n += table[key].release_matching(dead)
                if table[key].empty():
                    table.pop(key, None)
        # pending revocation notices die with the client
        for o in [o for o in self._revocation_notices
                  if o == owner or o.startswith(prefix)]:
            self._revocation_notices.pop(o, None)
        return n

    def lock_status(self) -> dict:
        """The wedge view (`volume status callpool` + dump_private):
        per-domain blocked-waiter counts and oldest-holder age, so an
        operator can SEE a wedge before revocation fires."""
        domains = []
        for kind, table in self._tables():
            for key, dom in table.items():
                if not dom.waiters and not dom.granted:
                    continue
                row = {"kind": kind, "granted": len(dom.granted),
                       "blocked": len(dom.waiters),
                       "oldest_holder_secs":
                           round(dom.oldest_holder_age(), 3),
                       "oldest_waiter_secs":
                           round(dom.oldest_waiter_age(), 3),
                       **self._describe_key(kind, key)}
                domains.append(row)
        # wedges first: most-blocked, then oldest holder
        domains.sort(key=lambda r: (-r["blocked"],
                                    -r["oldest_holder_secs"]))
        return {"blocked": self._blocked_counts(),
                "revoked": dict(self.revoked_counts),
                "domains": domains[:64]}

    def dump_private(self) -> dict:
        return {
            "inodelk_domains": len(self._inodelk),
            "entrylk_domains": len(self._entrylk),
            "posixlk_inodes": len(self._posixlk),
            "granted": sum(len(d.granted) for d in self._inodelk.values()),
            "waiting": sum(len(d.waiters) for d in self._inodelk.values()),
            **self.lock_status(),
        }
