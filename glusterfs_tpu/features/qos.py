"""Multi-tenant QoS plane — per-client token buckets, priority lanes,
and quota-driven backpressure.

The reference names the shape without ever assembling it: io-threads'
least-priority class and throttling knobs (io-threads.c), the tbf token
bucket (libglusterfs/src/throttle-tbf.c, used only by bitrot), and
``server.outstanding-rpc-limit``'s per-client admission gate
(rpcsvc.c:211-250).  This module is the assembly: one
:class:`QosEngine` per served brick top (and one per gateway process)
holds a pair of token buckets per client identity — fops/s and bytes/s
— consulted by ``protocol/server`` at FRAME ADMISSION, before the fop
ever enters the brick graph.

Verdicts, and why there are two throttle modes:

* **shed** — a rate-bucket overdraft refuses the frame with a
  retryable EAGAIN carrying ``xdata["qos-throttle"] = {retry-after,
  reason}``.  The refusal is ANSWERED over a healthy transport, so the
  client's PR-9 circuit breaker (which only counts transport failures)
  structurally cannot trip on shaping — shed-by-identity happens
  before the breaker ever sees trouble.  And because a shed frame was
  never dispatched, the client may safely retry ANY fop, not just
  idempotent ones.
* **shape** — the connection's read loop sleeps instead of erroring:
  soft-quota pressure (features/quota's over-soft-limit window) and
  the rebalance lane both want the traffic to COMPLETE, just slower.
  TCP flow control then shapes the sender.  Clients over soft quota
  get shaped, not errored; rebalance daemons (``origin="rebalance"``
  in the handshake creds) ride a shared paced lane sized by
  ``qos-rebalance-throttle`` (the lazy/normal/aggressive table) —
  shedding a migration daemon's non-idempotent fops would break the
  move, so that lane never sheds.

What is exempt, and why (``EXEMPT_FOPS``): lock-class fops (the same
deadlock rule as outstanding-rpc-limit — a shed unlock can never free
the blocked locks that filled the budget), and lease/fd teardown
(``lease``/``release``/``releasedir``): a recall's ack must never be
shed, so QoS never holds cache coherence hostage — and in particular
never recalls (or stalls the return of) a lease just to shape a
client.  Leased zero-wire readers never reach admission at all: their
reads are served from client-side caches at zero round trips, which is
the cheapest possible citizen.

Observability: THROTTLE_{START,STOP} lifecycle events fire on the
TRANSITION edge only (one START when a client first gets shaped, one
STOP after a full quiet window — the quorum-event discipline, not one
event per shed frame), the ``gftpu_qos_*`` families below, and a
per-client ``qos`` block in ``volume status clients``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable

from ..core.events import gf_event
from ..core import gflog
from ..core import metrics as _metrics
from ..core.options import parse_bool
from ..mgmt.svcutil import TokenBucket

log = gflog.get_logger("features.qos")

#: fops never charged to a client's buckets (see module docstring)
EXEMPT_FOPS = {"inodelk", "finodelk", "entrylk", "fentrylk", "lk",
               "lease", "release", "releasedir"}

#: write-path fops shaped under soft-quota pressure — features/quota's
#: enforced set plus the namespace creators that grow usage; delaying
#: reads buys the quota nothing
SOFT_SHAPED_FOPS = {"writev", "truncate", "ftruncate", "fallocate",
                    "create", "mknod", "mkdir"}

#: the rebalance lane's fops/s pacing per ``qos-rebalance-throttle``
#: mode — the lazy/normal/aggressive table the daemon's client-side
#: ThrottleWave expresses in migration width, re-expressed here as a
#: brick-side admission rate (aggressive = unpaced, 0 disables)
REBAL_LANE_FOPS = {"lazy": 64.0, "normal": 512.0, "aggressive": 0.0}


def _b(v: Any) -> bool:
    try:
        return parse_bool(v)
    except Exception:  # noqa: BLE001 - malformed option disables
        return False


def _f(v: Any, default: float = 0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def _ident_hex(identity: Any) -> str:
    """Event/status identity: full hex for bytes (the client-uid shape
    the rest of the status plane uses), str for gateway peer IPs."""
    if isinstance(identity, (bytes, bytearray)):
        return bytes(identity).hex()
    return str(identity)


class _ClientState:
    """Per-identity shaping state: the bucket pair + the throttle edge
    tracker behind THROTTLE_{START,STOP}."""

    __slots__ = ("fops", "bytes", "throttled", "reason", "since",
                 "last_hit", "shed_fops", "shed_bytes", "shaped_fops")

    def __init__(self) -> None:
        self.fops = TokenBucket(0.0)
        self.bytes = TokenBucket(0.0)
        self.throttled = False
        self.reason = ""
        self.since = 0.0
        self.last_hit = 0.0
        self.shed_fops = 0
        self.shed_bytes = 0
        self.shaped_fops = 0


class QosEngine:
    """Admission-control engine for one served top (or one gateway).

    ``opts_fn`` is read PER VERDICT (the ``outstanding-rpc-limit``
    live-reconfigure pattern): a ``volume set server.qos-*`` retunes
    running buckets on the next frame, no restart.  ``soft_fn`` yields
    the identities currently over their quota soft limit (wired to
    ``features/quota.qos_soft_clients`` by the server)."""

    def __init__(self, name: str, opts_fn: Callable[[], dict],
                 door: str = "brick",
                 soft_fn: Callable[[], Iterable] | None = None):
        self.name = name
        self.opts_fn = opts_fn
        self.door = door
        self.soft_fn = soft_fn
        self.clients: dict[Any, _ClientState] = {}
        self._rebal = TokenBucket(0.0)
        # family counters, labeled by throttle mode
        self.stats = {"shed": 0, "shaped": 0}
        self.stats_bytes = {"shed": 0, "shaped": 0}
        _ENGINES.add(self)

    # -- option reads (live) ----------------------------------------------

    def _opts(self) -> dict:
        try:
            return self.opts_fn() or {}
        except Exception:  # noqa: BLE001 - a dying graph must not shed
            return {}

    def enabled(self, opts: dict | None = None) -> bool:
        return _b((opts if opts is not None
                   else self._opts()).get("qos", False))

    def _window(self, opts: dict) -> float:
        return max(_f(opts.get("qos-shaped-window", 2.0), 2.0), 0.1)

    # -- the verdict -------------------------------------------------------

    def admit(self, identity: Any, fop: str = "", nbytes: int = 0,
              origin: str = "") -> tuple[str, float, str]:
        """One frame's verdict: ``("ok", 0, "")``, ``("shed",
        retry_after, reason)`` or ``("shape", delay, reason)``.

        ``nbytes`` is the wire frame size in hand (rx); reply bytes are
        charged after the fact via :meth:`charge` — the bucket borrows
        (goes negative) so a big readv's reply delays the NEXT
        admission instead of blocking this send."""
        opts = self._opts()
        if not self.enabled(opts):
            return ("ok", 0.0, "")
        now = time.monotonic()
        if origin == "rebalance":
            # the paced lane: migration fops complete, just slower —
            # shedding the daemon's non-idempotent moves would break
            # the migration.  One SHARED bucket: the lane budget is
            # per brick, not per daemon connection.
            rate = REBAL_LANE_FOPS.get(
                str(opts.get("qos-rebalance-throttle",
                             "normal") or "normal"), 512.0)
            if rate <= 0:
                return ("ok", 0.0, "")
            self._rebal.set_rate(rate)
            wait = self._rebal.try_take(1.0)
            if wait > 0:
                self.stats["shaped"] += 1
                self.stats_bytes["shaped"] += int(nbytes)
                return ("shape", min(wait, 1.0), "rebalance")
            return ("ok", 0.0, "")
        if fop in EXEMPT_FOPS:
            return ("ok", 0.0, "")
        st = self.clients.get(identity)
        if st is None:
            st = self.clients[identity] = _ClientState()
        burst_s = max(_f(opts.get("qos-burst", 1.0), 1.0), 0.001)
        frate = _f(opts.get("qos-fops-per-sec", 0))
        brate = _f(opts.get("qos-bytes-per-sec", 0))
        st.fops.set_rate(frate, frate * burst_s or None)
        st.bytes.set_rate(brate, brate * burst_s or None)
        wait = st.fops.try_take(1.0)
        if nbytes:
            wait = max(wait, st.bytes.try_take(float(nbytes)))
        if wait > 0:
            st.shed_fops += 1
            st.shed_bytes += int(nbytes)
            self.stats["shed"] += 1
            self.stats_bytes["shed"] += int(nbytes)
            self._hit(identity, st, "rate", now)
            return ("shed", wait, "rate")
        if self.soft_fn is not None and fop in SOFT_SHAPED_FOPS:
            try:
                soft = self.soft_fn()
            except Exception:  # noqa: BLE001 - quota probe must not shed
                soft = ()
            if identity in soft:
                delay = _f(opts.get("qos-soft-quota-delay", 0.05), 0.05)
                if delay > 0:
                    st.shaped_fops += 1
                    self.stats["shaped"] += 1
                    self.stats_bytes["shaped"] += int(nbytes)
                    self._hit(identity, st, "soft-quota", now)
                    return ("shape", delay, "soft-quota")
        self._maybe_stop(identity, st, self._window(opts), now)
        return ("ok", 0.0, "")

    def charge(self, identity: Any, nbytes: int) -> None:
        """Debit reply bytes against an EXISTING client's bytes bucket
        (borrowing — see :meth:`admit`).  Unknown identities (mgmt
        conns, pre-admission probes) are never charged."""
        st = self.clients.get(identity)
        if st is not None and nbytes:
            st.bytes.debit(float(nbytes))

    def lane(self, identity: Any, origin: str = "") -> str:
        """io-threads priority lane for this request: rebalance traffic
        and currently-shaped clients ride the least-priority class
        (io-threads' enable-least-priority model), everyone else keeps
        the per-fop priority table."""
        if not self.enabled():
            return ""
        if origin == "rebalance":
            return "least"
        st = self.clients.get(identity)
        return "least" if st is not None and st.throttled else ""

    # -- throttle lifecycle edges -----------------------------------------

    def _hit(self, identity: Any, st: _ClientState, reason: str,
             now: float) -> None:
        st.last_hit = now
        if not st.throttled:
            st.throttled = True
            st.reason = reason
            st.since = now
            gf_event("THROTTLE_START", volume=self.name, door=self.door,
                     client=_ident_hex(identity), reason=reason)

    def _maybe_stop(self, identity: Any, st: _ClientState,
                    window: float, now: float) -> None:
        if st.throttled and now - st.last_hit >= window:
            st.throttled = False
            gf_event("THROTTLE_STOP", volume=self.name, door=self.door,
                     client=_ident_hex(identity), reason=st.reason,
                     duration=round(now - st.since, 3))
            st.reason = ""

    def poll(self) -> None:
        """Sweep STOP edges for clients that went quiet without sending
        another frame (the admission path only sees active clients)."""
        window = self._window(self._opts())
        now = time.monotonic()
        for identity, st in list(self.clients.items()):
            self._maybe_stop(identity, st, window, now)

    def release_client(self, identity: Any) -> None:
        """Disconnect reap: a START without a matching STOP would read
        as still-throttled in the event history."""
        st = self.clients.pop(identity, None)
        if st is not None and st.throttled:
            gf_event("THROTTLE_STOP", volume=self.name, door=self.door,
                     client=_ident_hex(identity), reason=st.reason,
                     duration=round(time.monotonic() - st.since, 3))

    # -- views (status + metrics) -----------------------------------------

    def shaped_count(self) -> int:
        self.poll()
        return sum(1 for st in self.clients.values() if st.throttled)

    def client_view(self, identity: Any) -> dict:
        """The ``qos`` block of one ``volume status clients`` row."""
        opts = self._opts()
        st = self.clients.get(identity)
        if st is not None:
            self._maybe_stop(identity, st, self._window(opts),
                             time.monotonic())
        row = {"enabled": self.enabled(opts),
               "shaped": bool(st is not None and st.throttled),
               "reason": st.reason if st is not None else "",
               "shed_fops": st.shed_fops if st is not None else 0,
               "shed_bytes": st.shed_bytes if st is not None else 0,
               "shaped_fops": st.shaped_fops if st is not None else 0}
        if st is not None and row["enabled"]:
            row["tokens"] = {"fops": round(st.fops.level(), 1),
                             "bytes": round(st.bytes.level(), 1)}
        return row

    def _token_samples(self):
        for identity, st in self.clients.items():
            labels = {"server": self.name, "door": self.door,
                      "client": _ident_hex(identity)[:8]}
            yield {**labels, "bucket": "fops"}, st.fops.level()
            yield {**labels, "bucket": "bytes"}, st.bytes.level()


# live engines, scraped by the unified registry (weakref: a stopped
# server's engine ages out with the GC)
_ENGINES = _metrics.REGISTRY.register_objects(
    "gftpu_qos_throttled_fops_total", "counter",
    "frames refused (mode=shed: EAGAIN + retry-after notice) or "
    "delayed (mode=shaped: soft-quota / rebalance-lane pacing) by the "
    "QoS admission plane",
    lambda e: [({"server": e.name, "door": e.door, "mode": m}, v)
               for m, v in e.stats.items()])
_metrics.REGISTRY.register_objects(
    "gftpu_qos_throttled_bytes_total", "counter",
    "wire bytes of frames shed or shaped by the QoS admission plane",
    lambda e: [({"server": e.name, "door": e.door, "mode": m}, v)
               for m, v in e.stats_bytes.items()],
    live=_ENGINES)
_metrics.REGISTRY.register_objects(
    "gftpu_qos_shaped_clients", "gauge",
    "client identities currently inside a throttle window "
    "(THROTTLE_START fired, no STOP yet)",
    lambda e: [({"server": e.name, "door": e.door}, e.shaped_count())],
    live=_ENGINES)
_metrics.REGISTRY.register_objects(
    "gftpu_qos_tokens", "gauge",
    "current token balance per client bucket (negative = borrowed "
    "against reply bytes already sent)",
    lambda e: e._token_samples(), live=_ENGINES)
