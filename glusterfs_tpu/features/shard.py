"""features/shard — split big files into fixed-size shard files.

Reference: xlators/features/shard (8.1k LoC; shard.c:3428 option
``shard-block-size``): block 0 lives at the file's own path; blocks
1..N live at ``/.shard/<gfid-hex>.<N>``; the true file size rides in
the ``trusted.glusterfs.shard.file-size`` xattr of block 0.  Large-file
(VM image) use case: writes touch only the shards they cover."""

from __future__ import annotations

import errno

from ..core.fops import FopError
from ..core.iatt import Iatt
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option

SHARD_DIR = ".shard"
XA_SIZE = "trusted.glusterfs.shard.file-size"


@register("features/shard")
class ShardLayer(Layer):
    OPTIONS = (
        Option("shard-block-size", "size", default="64MB", min=4096),
        Option("shard-lru-limit", "int", default=16384, min=64,
               description="cached per-inode shard metadata entries "
                           "(features.shard-lru-limit, shard.c inode "
                           "LRU)"),
        Option("shard-deletion-rate", "int", default=100, min=1,
               description="shards removed per batch when a sharded "
                           "file is unlinked (features.shard-deletion-"
                           "rate): paces the background cleanup so a "
                           "huge file's delete doesn't monopolize the "
                           "brick"),
    )

    async def init(self):
        await super().init()
        try:
            await self.children[0].mkdir(Loc("/" + SHARD_DIR), 0o755)
        except FopError as e:
            if e.err != errno.EEXIST:
                raise

    # -- helpers -----------------------------------------------------------

    def _bs(self) -> int:
        return self.opts["shard-block-size"]

    def _shard_path(self, gfid: bytes, idx: int) -> str:
        return f"/{SHARD_DIR}/{gfid.hex()}.{idx}"

    def _size_cache(self):
        import collections

        c = getattr(self, "_sizes", None)
        if c is None:
            c = self._sizes = collections.OrderedDict()
        return c

    def _size_cache_put(self, gfid: bytes, size: int) -> None:
        c = self._size_cache()
        c[gfid] = size
        c.move_to_end(gfid)
        while len(c) > int(self.opts["shard-lru-limit"]):
            c.popitem(last=False)  # features.shard-lru-limit

    async def _true_size(self, loc_or_fd) -> int:
        gfid = getattr(loc_or_fd, "gfid", None)
        cache = self._size_cache()
        if gfid is not None and gfid in cache:
            cache.move_to_end(gfid)
            return cache[gfid]
        try:
            if isinstance(loc_or_fd, FdObj):
                out = await self.children[0].fgetxattr(loc_or_fd, XA_SIZE)
            else:
                out = await self.children[0].getxattr(loc_or_fd, XA_SIZE)
            size = int(out[XA_SIZE].decode())
        except FopError:
            # unsharded legacy file: base size is the size
            if isinstance(loc_or_fd, FdObj):
                size = (await self.children[0].fstat(loc_or_fd)).size
            else:
                size = (await self.children[0].stat(loc_or_fd)).size
        if gfid is not None:
            self._size_cache_put(gfid, size)
        return size

    async def _set_size(self, fd: FdObj, size: int) -> None:
        await self.children[0].fsetxattr(
            fd, {XA_SIZE: str(size).encode()})
        if fd.gfid is not None:
            self._size_cache_put(fd.gfid, size)

    async def _shard_write(self, gfid: bytes, idx: int, data: bytes,
                           offset: int, base_fd: FdObj) -> None:
        if idx == 0:
            await self.children[0].writev(base_fd, data, offset)
            return
        path = self._shard_path(gfid, idx)
        loc = Loc(path)
        try:
            sfd = await self.children[0].open(loc, 2)
        except FopError as e:
            if e.err != errno.ENOENT:
                raise
            sfd, _ = await self.children[0].create(loc, 0, 0o600)
        try:
            await self.children[0].writev(sfd, data, offset)
        finally:
            await self.children[0].release(sfd)

    async def _shard_read(self, gfid: bytes, idx: int, size: int,
                          offset: int, base_fd: FdObj) -> bytes:
        if idx == 0:
            return await self.children[0].readv(base_fd, size, offset)
        loc = Loc(self._shard_path(gfid, idx))
        try:
            sfd = await self.children[0].open(loc, 0)
        except FopError as e:
            if e.err == errno.ENOENT:
                return b"\0" * size  # hole
            raise
        try:
            out = await self.children[0].readv(sfd, size, offset)
            # readv results may be views (EC decode buffers, wire blob
            # lane) — own them before padding
            return bytes(out).ljust(size, b"\0")
        finally:
            await self.children[0].release(sfd)

    # -- fops --------------------------------------------------------------

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        fd, ia = await self.children[0].create(loc, flags, mode, xdata)
        await self._set_size(fd, 0)
        return fd, ia

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        data = bytes(data)
        bs = self._bs()
        true_size = await self._true_size(fd)
        pos = offset
        remaining = data
        while remaining:
            idx = pos // bs
            within = pos - idx * bs
            take = min(bs - within, len(remaining))
            await self._shard_write(fd.gfid, idx, remaining[:take],
                                    within, fd)
            remaining = remaining[take:]
            pos += take
        new_size = max(true_size, offset + len(data))
        if new_size != true_size:
            await self._set_size(fd, new_size)
        ia = await self.children[0].fstat(fd)
        ia = Iatt(**{**ia.__dict__})
        ia.size = new_size
        return ia

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        bs = self._bs()
        true_size = await self._true_size(fd)
        if offset >= true_size:
            return b""
        size = min(size, true_size - offset)
        out = bytearray()
        pos = offset
        end = offset + size
        while pos < end:
            idx = pos // bs
            within = pos - idx * bs
            take = min(bs - within, end - pos)
            chunk = await self._shard_read(fd.gfid, idx, take, within, fd)
            out += bytes(chunk).ljust(take, b"\0")  # holes read as zeros
            pos += take
        return bytes(out)

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        ia = await self.children[0].fstat(fd, xdata)
        ia = Iatt(**{**ia.__dict__})
        ia.size = await self._true_size(fd)
        return ia

    async def stat(self, loc: Loc, xdata: dict | None = None):
        ia = await self.children[0].stat(loc, xdata)
        if not ia.is_dir():
            ia = Iatt(**{**ia.__dict__})
            ia.size = await self._true_size(loc)
        return ia

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        ia, xd = await self.children[0].lookup(loc, xdata)
        if not ia.is_dir() and not loc.path.startswith("/" + SHARD_DIR):
            ia = Iatt(**{**ia.__dict__})
            ia.size = await self._true_size(loc)
        return ia, xd

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        bs = self._bs()
        true_size = await self._true_size(fd)
        last_keep = (size + bs - 1) // bs  # first shard index to drop
        old_last = (true_size + bs - 1) // bs
        for idx in range(max(1, last_keep), old_last):
            try:
                await self.children[0].unlink(
                    Loc(self._shard_path(fd.gfid, idx)))
            except FopError:
                pass
        if size <= bs:
            await self.children[0].ftruncate(fd, size, xdata)
        elif size % bs:
            idx = size // bs
            if idx > 0:
                loc = Loc(self._shard_path(fd.gfid, idx))
                try:
                    await self.children[0].truncate(loc, size % bs)
                except FopError:
                    pass
        await self._set_size(fd, size)
        ia = await self.children[0].fstat(fd)
        ia = Iatt(**{**ia.__dict__})
        ia.size = size
        return ia

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        fd = await self.children[0].open(loc, 2)
        try:
            return await self.ftruncate(fd, size, xdata)
        finally:
            await self.children[0].release(fd)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        try:
            import asyncio

            ia, _ = await self.children[0].lookup(loc)
            bs = self._bs()
            true_size = await self._true_size(loc)
            rate = int(self.opts["shard-deletion-rate"])
            nshards = (true_size + bs - 1) // bs
            for batch_start in range(1, nshards, rate):
                for idx in range(batch_start,
                                 min(batch_start + rate, nshards)):
                    try:
                        await self.children[0].unlink(
                            Loc(self._shard_path(ia.gfid, idx)))
                    except FopError:
                        pass
                # features.shard-deletion-rate: yield between batches
                # so a huge delete interleaves with client fops
                await asyncio.sleep(0)
        except FopError:
            pass
        return await self.children[0].unlink(loc, xdata)

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        entries = await self.children[0].readdir(fd, size, offset, xdata)
        return [(n, ia) for n, ia in entries if n != SHARD_DIR]

    async def readdirp(self, fd: FdObj, size: int = 0, offset: int = 0,
                       xdata: dict | None = None):
        entries = await self.children[0].readdirp(fd, size, offset, xdata)
        return [(n, ia) for n, ia in entries if n != SHARD_DIR]

    def dump_private(self) -> dict:
        return {"shard_block_size": self._bs()}
