"""features/utime — client-driven time consistency.

Reference: xlators/features/utime (+ posix-metadata ctime): every
replica/fragment brick stamping mtime from its own clock makes times
diverge across copies; the utime xlator stamps the CLIENT's clock into
the request so every brick stores the same instant.  Here: mutating
fops get ``xdata["frame-time"]``; the posix store applies it to mtime
(atime preserved; ctime is kernel-managed and advances with the stamp
syscall itself — the reference needs posix-metadata's own ctime store
for full ctime control, which this build folds into mtime parity)."""

from __future__ import annotations

import time

from ..core.fops import WRITE_FOPS
from ..core.layer import Layer, register

FRAME_TIME = "frame-time"


@register("features/utime")
class UtimeLayer(Layer):
    pass


def _stamping(op_name: str):
    async def impl(self, *args, **kwargs):
        from ..core.virtfs import call_with_xdata

        # callers pass xdata positionally as often as by keyword:
        # bind against the child's signature and merge there
        return await call_with_xdata(self.children[0], op_name, args,
                                     kwargs,
                                     {FRAME_TIME: time.time()})
    impl.__name__ = op_name
    return impl


for _f in WRITE_FOPS:
    setattr(UtimeLayer, _f.value, _stamping(_f.value))
