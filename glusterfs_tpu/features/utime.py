"""features/utime — client-driven time consistency.

Reference: xlators/features/utime (+ posix-metadata ctime): every
replica/fragment brick stamping mtime from its own clock makes times
diverge across copies; the utime xlator stamps the CLIENT's clock into
the request so every brick stores the same instant.  Here: mutating
fops get ``xdata["frame-time"]``; the posix store applies it to mtime
(atime preserved; ctime is kernel-managed and advances with the stamp
syscall itself — the reference needs posix-metadata's own ctime store
for full ctime control, which this build folds into mtime parity)."""

from __future__ import annotations

import time

from ..core.fops import WRITE_FOPS
from ..core.layer import Layer, register

FRAME_TIME = "frame-time"


@register("features/utime")
class UtimeLayer(Layer):
    from ..core.options import Option as _Opt

    OPTIONS = (
        _Opt("ctime", "bool", default="on",
             description="stamp the CLIENT clock into mutating fops "
                         "(features.ctime); off = each brick stamps "
                         "its own clock and times may diverge across "
                         "copies"),
        _Opt("noatime", "bool", default="on",
             description="skip access-time stamping on reads "
                         "(ctime.noatime); off stamps reads too, one "
                         "utime per read wave"),
    )

    async def readv(self, fd, size, offset, xdata=None):
        if self.opts["ctime"] and not self.opts["noatime"]:
            xdata = dict(xdata or {})
            xdata[FRAME_TIME + "-atime"] = time.time()
        return await self.children[0].readv(fd, size, offset, xdata)


def _stamping(op_name: str):
    async def impl(self, *args, **kwargs):
        from ..core.virtfs import call_with_xdata

        if not self.opts["ctime"]:  # features.ctime off: brick clocks
            return await getattr(self.children[0], op_name)(*args,
                                                            **kwargs)
        # callers pass xdata positionally as often as by keyword:
        # bind against the child's signature and merge there
        return await call_with_xdata(self.children[0], op_name, args,
                                     kwargs,
                                     {FRAME_TIME: time.time()})
    impl.__name__ = op_name
    return impl


for _f in WRITE_FOPS:
    setattr(UtimeLayer, _f.value, _stamping(_f.value))
