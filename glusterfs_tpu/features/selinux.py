"""features/selinux — SELinux label xattr translation.

Reference: xlators/features/selinux (selinux.c): clients get/set
``security.selinux`` but bricks must not write the security namespace
(it would relabel the brick's own files); the xlator maps it to
``trusted.glusterfs.selinux`` at rest and back on the way out."""

from __future__ import annotations

from ..core.layer import FdObj, Layer, Loc, register

CLIENT_KEY = "security.selinux"
STORE_KEY = "trusted.glusterfs.selinux"


def _to_store(xattrs: dict) -> dict:
    return {STORE_KEY if k == CLIENT_KEY else k: v
            for k, v in xattrs.items()}


def _to_client(xattrs: dict) -> dict:
    return {CLIENT_KEY if k == STORE_KEY else k: v
            for k, v in xattrs.items()}


@register("features/selinux")
class SelinuxLayer(Layer):
    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        return await self.children[0].setxattr(loc, _to_store(xattrs),
                                               flags, xdata)

    async def fsetxattr(self, fd: FdObj, xattrs: dict, flags: int = 0,
                        xdata: dict | None = None):
        return await self.children[0].fsetxattr(fd, _to_store(xattrs),
                                                flags, xdata)

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        ret = await self.children[0].getxattr(
            loc, STORE_KEY if name == CLIENT_KEY else name, xdata)
        return _to_client(ret or {})

    async def removexattr(self, loc: Loc, name: str,
                          xdata: dict | None = None):
        return await self.children[0].removexattr(
            loc, STORE_KEY if name == CLIENT_KEY else name, xdata)

    async def fgetxattr(self, fd: FdObj, name: str | None = None,
                        xdata: dict | None = None):
        ret = await self.children[0].fgetxattr(
            fd, STORE_KEY if name == CLIENT_KEY else name, xdata)
        return _to_client(ret or {})

    async def fremovexattr(self, fd: FdObj, name: str,
                           xdata: dict | None = None):
        return await self.children[0].fremovexattr(
            fd, STORE_KEY if name == CLIENT_KEY else name, xdata)
