"""features/upcall — server-side client registry + cache invalidation.

Reference: xlators/features/upcall/src/upcall.c:48-207
(upcall_client_cache_invalidate, add_upcall_client): the brick tracks
which clients recently touched each inode and, when another client
mutates it, calls back an invalidation that md-cache consumes — the
mechanism that keeps two clients on one volume metadata-coherent without
TTL waiting.

Here the layer sits in the brick stack.  The serving BrickServer injects
the current RPC peer identity through ``rpc.wire.CURRENT_CLIENT`` (a
ContextVar set per dispatch) and registers itself as the event sink; the
layer pushes ``MT_EVENT`` frames (rpc/wire.py:25) to every *other*
registered client within ``cache-invalidation-timeout`` of its last
access.  protocol/client surfaces the frames as ``Event.UPCALL`` graph
notifications; performance/md-cache invalidates on them.
"""

from __future__ import annotations

import time
from typing import Callable

from ..core.fops import Fop, FopError, WRITE_FOPS
from ..core.iatt import Iatt
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog
from ..rpc.wire import CURRENT_CLIENT

log = gflog.get_logger("upcall")

# fops whose reply a client may cache -> register interest
#   (upcall.c upcall_local_init call sites)
_CACHE_FOPS = {Fop.LOOKUP, Fop.STAT, Fop.FSTAT, Fop.READV, Fop.GETXATTR,
               Fop.FGETXATTR, Fop.READDIR, Fop.READDIRP, Fop.OPEN,
               Fop.OPENDIR}


@register("features/upcall")
class UpcallLayer(Layer):
    OPTIONS = (
        Option("cache-invalidation", "bool", default="on"),
        Option("cache-invalidation-timeout", "time", default="60",
               description="forget a client's interest in an inode after "
                           "this idle time (features.cache-invalidation-"
                           "timeout)"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # gfid -> {client identity -> last access time}
        self._reg: dict[bytes, dict[bytes, float]] = {}
        self._sink: Callable[[list[bytes], dict], None] | None = None
        self.sent = 0
        self._ops = 0  # amortized-sweep counter

    def set_upcall_sink(self, sink: Callable[[list[bytes], dict], None]):
        """BrickServer hands us its event-push callback at serve time."""
        self._sink = sink

    def release_client(self, identity: bytes) -> None:
        """Disconnect cleanup (client_t reap): drop all registrations."""
        for gfid in list(self._reg):
            regs = self._reg[gfid]
            regs.pop(identity, None)
            if not regs:
                del self._reg[gfid]

    # -- registry ----------------------------------------------------------

    def _touch(self, gfid: bytes, client: bytes) -> None:
        self._reg.setdefault(gfid, {})[client] = time.monotonic()
        # amortized registry sweep: read-only inodes are never visited
        # by _interested, so without this the registry would grow
        # without bound on a long-lived brick
        self._ops += 1
        if self._ops % 4096 == 0:
            self._sweep()

    def _sweep(self) -> None:
        horizon = time.monotonic() - self.opts["cache-invalidation-timeout"]
        for gfid in list(self._reg):
            regs = self._reg[gfid]
            for c in [c for c, t in regs.items() if t < horizon]:
                del regs[c]
            if not regs:
                del self._reg[gfid]

    def _interested(self, gfid: bytes, but_not: bytes | None) -> list[bytes]:
        regs = self._reg.get(gfid)
        if not regs:
            return []
        horizon = time.monotonic() - self.opts["cache-invalidation-timeout"]
        for c in [c for c, t in regs.items() if t < horizon]:
            del regs[c]
        if not regs:
            del self._reg[gfid]
            return []
        return [c for c in regs if c != but_not]

    def _notify_mutation(self, gfid: bytes, client: bytes | None,
                         fop: str) -> None:
        if self._sink is None or not self.opts["cache-invalidation"]:
            return
        targets = self._interested(gfid, client)
        if targets:
            self.sent += 1
            self._sink(targets, {"event": "cache-invalidation",
                                 "gfid": gfid, "fop": fop})

    @staticmethod
    def _gfids_of(args: tuple, ret) -> set[bytes]:
        out = set()
        for a in args:
            if isinstance(a, Loc) and a.gfid:
                out.add(a.gfid)
            elif isinstance(a, FdObj) and a.gfid:
                out.add(a.gfid)
        if isinstance(ret, Iatt) and ret.gfid:
            out.add(ret.gfid)
        elif isinstance(ret, tuple):
            for r in ret:
                if isinstance(r, Iatt) and r.gfid:
                    out.add(r.gfid)
        return out

    def dump_private(self) -> dict:
        return {"tracked_inodes": len(self._reg),
                "invalidations_sent": self.sent}


def _observing(op_name: str, mutates: bool):
    async def fop(self, *args, **kwargs):
        ret = await getattr(self.children[0], op_name)(*args, **kwargs)
        client = CURRENT_CLIENT.get(None)
        for gfid in self._gfids_of(args, ret):
            if mutates:
                self._notify_mutation(gfid, client, op_name)
            if client is not None:
                self._touch(gfid, client)
        return ret
    fop.__name__ = op_name
    return fop


for _f in _CACHE_FOPS:
    setattr(UpcallLayer, _f.value, _observing(_f.value, mutates=False))
for _f in WRITE_FOPS:
    setattr(UpcallLayer, _f.value, _observing(_f.value, mutates=True))


async def _upcall_rename(self, oldloc: Loc, newloc: Loc,
                         xdata: dict | None = None):
    """Rename needs more than the generic write wrapper: a REPLACED
    destination dies in the rename, but the args only carry the
    source's gfid — resolve the destination's current identity first
    (a local brick-graph lookup, no wire hop) so clients caching the
    victim get invalidated too (upcall.c does the same via the
    newloc inode)."""
    victim = None
    try:
        ia, _ = await self.children[0].lookup(
            Loc(newloc.path, parent=newloc.parent, name=newloc.name))
        victim = ia.gfid
    except FopError:
        pass  # fresh destination: nothing to invalidate
    ret = await self.children[0].rename(oldloc, newloc, xdata)
    client = CURRENT_CLIENT.get(None)
    gfids = self._gfids_of((oldloc, newloc), ret)
    if victim:
        gfids.add(victim)
    for gfid in gfids:
        self._notify_mutation(gfid, client, "rename")
        if client is not None:
            self._touch(gfid, client)
    return ret


UpcallLayer.rename = _upcall_rename
