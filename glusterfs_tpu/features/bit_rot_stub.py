"""features/bit-rot-stub — brick-side quarantine for corrupted objects.

Reference: xlators/features/bit-rot/src/stub/bit-rot-stub.c:29-40: the
stub rides every brick, maintains the object signature/version xattrs
for bitd (the signer/scrubber daemon) and fences access to objects the
scrubber marked bad — a corrupted replica/fragment must never be served
to a client or used as a heal source.

TPU-build mechanisms: the signer stores
``trusted.bit-rot.signature`` = JSON {sha256, ts}; the scrubber marks
``trusted.bit-rot.bad-file``.  The stub keeps the quarantine set in
memory (rebuilt at init through posix's xattr-scan virtual), denies
readv on bad objects with EIO, and lifts the quarantine when the object
is rewritten (the heal path: shd decodes from good bricks and writevs
through this stub, which clears the marker and the stale signature).
"""

from __future__ import annotations

import errno

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog
from ..storage.posix import XA_SCAN_PREFIX

log = gflog.get_logger("bitrot-stub")

XA_SIG = "trusted.bit-rot.signature"
XA_BAD = "trusted.bit-rot.bad-file"
# xdata flag the heal engines set on rebuild writes: only those may
# touch (and ultimately unquarantine) a bad object — a client's partial
# write over a corrupt file must not lift the fence
HEAL_WRITE = "glusterfs_tpu.heal-write"


@register("features/bit-rot-stub")
class BitRotStubLayer(Layer):
    OPTIONS = (
        Option("bitrot", "bool", default="on"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._bad: set[bytes] = set()

    async def init(self):
        await super().init()
        # restart-survival: reload the persisted quarantine
        try:
            r = await self.children[0].getxattr(
                Loc("/"), XA_SCAN_PREFIX + XA_BAD)
            self._bad = {bytes.fromhex(h) for h in
                         r[XA_SCAN_PREFIX + XA_BAD].decode().split()}
        except FopError:
            self._bad = set()
        if self._bad:
            log.warning(1, "%s: %d quarantined objects", self.name,
                        len(self._bad))

    def _deny(self, gfid: bytes) -> bool:
        return self.opts["bitrot"] and gfid in self._bad

    # -- fencing -----------------------------------------------------------

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        if self._deny(fd.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].readv(fd, size, offset, xdata)

    async def rchecksum(self, fd: FdObj, offset: int, length: int,
                        xdata: dict | None = None):
        if self._deny(fd.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].rchecksum(fd, offset, length, xdata)

    async def xorv(self, fd: FdObj, data, offset: int,
                   xdata: dict | None = None):
        # parity-delta applies are client data writes (heal rebuilds
        # full fragments via writev, never xorv): a quarantined object
        # stays fenced against them like any other mutation
        if self._deny(fd.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].xorv(fd, data, offset, xdata)

    # -- the rest of the content-mutating vocabulary (graft-lint GL01
    # fence parity): a quarantined object's bytes are EVIDENCE — they
    # must stay exactly as the scrubber found them until heal rebuilds
    # (writev + HEAL_WRITE) or the operator removes the object --------

    async def truncate(self, loc, size: int, xdata: dict | None = None):
        if self._deny(loc.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].truncate(loc, size, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        if self._deny(fd.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].ftruncate(fd, size, xdata)

    async def fallocate(self, fd: FdObj, mode: int, offset: int,
                        length: int, xdata: dict | None = None):
        if self._deny(fd.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].fallocate(fd, mode, offset,
                                                length, xdata)

    async def discard(self, fd: FdObj, offset: int, length: int,
                      xdata: dict | None = None):
        if self._deny(fd.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].discard(fd, offset, length, xdata)

    async def zerofill(self, fd: FdObj, offset: int, length: int,
                       xdata: dict | None = None):
        if self._deny(fd.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].zerofill(fd, offset, length,
                                               xdata)

    async def put(self, loc, data, *args, **kwargs):
        # replacing a quarantined object's body via put would destroy
        # the evidence without a heal (posix serves put as
        # create+writev BELOW this fence)
        if self._deny(loc.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].put(loc, data, *args, **kwargs)

    async def copy_file_range(self, fd_in: FdObj, off_in: int,
                              fd_out: FdObj, off_out: int, length: int,
                              xdata: dict | None = None):
        # source: never serve corrupt bytes; destination: never write
        # over quarantined content
        if self._deny(fd_in.gfid) or self._deny(fd_out.gfid):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].copy_file_range(
            fd_in, off_in, fd_out, off_out, length, xdata)

    async def writev(self, fd: FdObj, data: bytes, offset: int,
                     xdata: dict | None = None):
        healing = bool((xdata or {}).get(HEAL_WRITE))
        if self._deny(fd.gfid) and not healing:
            # a client writing over a corrupt object would neither fix
            # it nor leave a heal trigger — keep it fenced (the
            # reference only lets internal rebuild writes through)
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        ret = await self.children[0].writev(fd, data, offset, xdata)
        if healing and fd.gfid in self._bad:
            # rebuild in progress (under the cluster heal lock): lift
            # the quarantine and drop the now-stale signature
            self._bad.discard(fd.gfid)
            gloc = Loc(fd.path, gfid=fd.gfid)
            for key in (XA_BAD, XA_SIG):
                try:
                    await self.children[0].removexattr(gloc, key)
                except FopError:
                    pass
        return ret

    async def xattrop(self, loc: Loc, op: str, xattrs: dict,
                      xdata: dict | None = None):
        if loc.gfid is not None and self._deny(loc.gfid) and \
                not (xdata or {}).get(HEAL_WRITE):
            # counter updates are mutations too: a client's DELAYED
            # post-op (eager-window commit) landing after the scrub
            # zeroed this brick's version would bump it back level with
            # the good bricks and erase the heal direction
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].xattrop(loc, op, xattrs, xdata)

    async def fxattrop(self, fd: FdObj, op: str, xattrs: dict,
                       xdata: dict | None = None):
        if self._deny(fd.gfid) and not (xdata or {}).get(HEAL_WRITE):
            raise FopError(errno.EIO, "object quarantined (bit-rot)")
        return await self.children[0].fxattrop(fd, op, xattrs, xdata)

    # -- quarantine bookkeeping (bitd writes markers through us) -----------

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        ret = await self.children[0].setxattr(loc, xattrs, flags, xdata)
        if XA_BAD in xattrs:
            gfid = loc.gfid
            if gfid is None:
                try:
                    gfid = (await self.children[0].lookup(loc))[0].gfid
                except FopError:
                    gfid = None
            if gfid is not None:
                self._bad.add(gfid)
                log.warning(2, "%s: quarantined %s (%s)", self.name,
                            gfid.hex(), loc.path)
        return ret

    async def removexattr(self, loc: Loc, name: str,
                          xdata: dict | None = None):
        ret = await self.children[0].removexattr(loc, name, xdata)
        if name == XA_BAD and loc.gfid is not None:
            self._bad.discard(loc.gfid)
        return ret

    def dump_private(self) -> dict:
        return {"quarantined": sorted(g.hex() for g in self._bad)}
