"""features/worm — write-once-read-many enforcement.

Reference: xlators/features/read-only/worm.c.  Two modes:

* volume-level (``worm on``): files may be created and written once;
  overwrites/truncates/unlinks deny with EROFS.
* file-level (``worm-file-level on``, worm.c worm_state_transition):
  a file left unmodified for ``auto-commit-period`` transitions to a
  RETAINED state (persisted in a ``trusted.worm.state`` xattr holding
  {start, period}); retained files deny every mutation until
  ``start + period`` passes, after which ``worm-files-deletable``
  decides whether unlink (alone) is allowed.  ``retention-mode``
  enterprise refuses to shorten a live retention; relax allows it.
"""

from __future__ import annotations

import errno
import json
import time

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option

XA_STATE = "trusted.worm.state"


@register("features/worm")
class WormLayer(Layer):
    OPTIONS = (
        Option("worm", "bool", default="on"),
        Option("worm-file-level", "bool", default="off",
               description="per-file WORM with retention (worm.c "
                           "worm_state_transition) instead of the "
                           "whole-volume write-once gate"),
        Option("worm-files-deletable", "bool", default="on",
               description="expired-retention files may be unlinked "
                           "(features.worm-files-deletable)"),
        Option("default-retention-period", "time", default="120",
               description="retention seconds stamped at the WORM "
                           "transition (features.default-retention-"
                           "period)"),
        Option("auto-commit-period", "time", default="180",
               description="idle seconds after the last modification "
                           "before a file turns WORM "
                           "(features.auto-commit-period)"),
        Option("retention-mode", "enum", default="relax",
               values=("relax", "enterprise"),
               description="enterprise: a live retention can only be "
                           "extended (features.retention-mode)"),
    )

    def _on(self) -> bool:
        return bool(self.opts["worm"]) and \
            not self.opts["worm-file-level"]

    def _file_level(self) -> bool:
        return bool(self.opts["worm-file-level"])

    async def _state(self, loc: Loc):
        """(retained, expired) after a lazy state transition."""
        try:
            x = await self.children[0].getxattr(loc, XA_STATE)
            st = json.loads(bytes(x[XA_STATE]))
        except (FopError, ValueError, KeyError):
            st = None
        now = time.time()
        if st is None:
            try:
                ia, _ = await self.children[0].lookup(loc)
            except FopError:
                return False, False
            if now - ia.mtime < self.opts["auto-commit-period"]:
                return False, False  # still in its commit window
            st = {"start": now,
                  "period": float(self.opts["default-retention-period"])}
            try:  # the lazy transition (worm_state_transition)
                await self.children[0].setxattr(
                    loc, {XA_STATE: json.dumps(st).encode()})
            except FopError:
                pass
        return True, now >= st["start"] + st["period"]

    async def _deny_file_level(self, loc: Loc, unlinking: bool = False):
        retained, expired = await self._state(loc)
        if not retained:
            return
        if unlinking and expired and self.opts["worm-files-deletable"]:
            return
        raise FopError(errno.EROFS, "worm: file retained")

    async def xorv(self, fd: FdObj, data, offset: int,
                   xdata: dict | None = None):
        # the parity-delta apply mutates stored bytes exactly like an
        # overwriting writev (read-xor-write is ALWAYS an overwrite):
        # the same retention fences must hold, or a delta wave's parity
        # half would slip past WORM while its data half is denied
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        elif self._on():
            raise FopError(errno.EROFS, "worm: overwrite denied")
        return await self.children[0].xorv(fd, data, offset, xdata)

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        elif self._on():
            ia = await self.children[0].fstat(fd)
            if offset < ia.size:
                raise FopError(errno.EROFS, "worm: overwrite denied")
        return await self.children[0].writev(fd, data, offset, xdata)

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(loc)
        elif self._on():
            raise FopError(errno.EROFS, "worm: truncate denied")
        return await self.children[0].truncate(loc, size, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        elif self._on():
            raise FopError(errno.EROFS, "worm: truncate denied")
        return await self.children[0].ftruncate(fd, size, xdata)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(loc, unlinking=True)
        elif self._on():
            raise FopError(errno.EROFS, "worm: unlink denied")
        return await self.children[0].unlink(loc, xdata)

    async def rename(self, oldloc: Loc, newloc: Loc,
                     xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(oldloc)
        elif self._on():
            raise FopError(errno.EROFS, "worm: rename denied")
        return await self.children[0].rename(oldloc, newloc, xdata)

    # -- the write vocabulary's long tail (graft-lint GL01 fence
    # parity: PR 10 had to fence xorv here after the fact; these
    # siblings had the same gap) ------------------------------------------

    async def link(self, oldloc: Loc, newloc: Loc,
                   xdata: dict | None = None):
        # a new name for a retained inode re-opens it to namespace
        # mutation (reference worm_link denies)
        if self._file_level():
            await self._deny_file_level(oldloc)
        elif self._on():
            raise FopError(errno.EROFS, "worm: link denied")
        return await self.children[0].link(oldloc, newloc, xdata)

    async def setattr(self, loc: Loc, attrs: dict, valid: int = 0,
                      xdata: dict | None = None):
        # retention state rides mtime (worm_state_transition keys off
        # it): a retained file's metadata is frozen; volume-level worm
        # fences data only, like the reference
        if self._file_level():
            await self._deny_file_level(loc)
        return await self.children[0].setattr(loc, attrs, valid, xdata)

    async def fsetattr(self, fd: FdObj, attrs: dict, valid: int = 0,
                       xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        return await self.children[0].fsetattr(fd, attrs, valid, xdata)

    async def fallocate(self, fd: FdObj, mode: int, offset: int,
                        length: int, xdata: dict | None = None):
        # same rule as writev: pure extension (append analog) passes
        # volume-level worm, touching committed bytes does not
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        elif self._on():
            ia = await self.children[0].fstat(fd)
            if offset < ia.size:
                raise FopError(errno.EROFS, "worm: overwrite denied")
        return await self.children[0].fallocate(fd, mode, offset,
                                                length, xdata)

    async def discard(self, fd: FdObj, offset: int, length: int,
                      xdata: dict | None = None):
        # hole-punching always mutates committed bytes
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        elif self._on():
            raise FopError(errno.EROFS, "worm: discard denied")
        return await self.children[0].discard(fd, offset, length, xdata)

    async def zerofill(self, fd: FdObj, offset: int, length: int,
                       xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        elif self._on():
            ia = await self.children[0].fstat(fd)
            if offset < ia.size:
                raise FopError(errno.EROFS, "worm: overwrite denied")
        return await self.children[0].zerofill(fd, offset, length,
                                               xdata)

    async def put(self, loc: Loc, data, *args, **kwargs):
        # put of an EXISTING object is a whole-body overwrite (posix
        # serves it as create+writev below every fence — it must be
        # caught here); put of a new object is the allowed create half
        if self._file_level():
            await self._deny_file_level(loc)
        elif self._on():
            try:
                await self.children[0].lookup(loc)
            except FopError:
                pass  # new object: write-once create is allowed
            else:
                raise FopError(errno.EROFS, "worm: overwrite denied")
        return await self.children[0].put(loc, data, *args, **kwargs)

    async def copy_file_range(self, fd_in: FdObj, off_in: int,
                              fd_out: FdObj, off_out: int, length: int,
                              xdata: dict | None = None):
        # the destination half is a writev (posix re-dispatches it
        # BELOW this fence): apply writev's exact rules to fd_out
        if self._file_level():
            await self._deny_file_level(Loc(fd_out.path,
                                            gfid=fd_out.gfid))
        elif self._on():
            ia = await self.children[0].fstat(fd_out)
            if off_out < ia.size:
                raise FopError(errno.EROFS, "worm: overwrite denied")
        return await self.children[0].copy_file_range(
            fd_in, off_in, fd_out, off_out, length, xdata)

    async def removexattr(self, loc: Loc, name: str,
                          xdata: dict | None = None):
        # stripping trusted.worm.state would silently de-WORM a
        # retained file
        if self._file_level() and name == XA_STATE:
            raise FopError(errno.EPERM,
                           "worm: retention state is not removable")
        return await self.children[0].removexattr(loc, name, xdata)

    async def fremovexattr(self, fd: FdObj, name: str,
                           xdata: dict | None = None):
        if self._file_level() and name == XA_STATE:
            raise FopError(errno.EPERM,
                           "worm: retention state is not removable")
        return await self.children[0].fremovexattr(fd, name, xdata)

    async def fsetxattr(self, fd: FdObj, xattrs: dict, flags: int = 0,
                        xdata: dict | None = None):
        # fd twin of setxattr: the same retention-adjust policing
        if self._file_level() and XA_STATE in xattrs:
            return await self.setxattr(Loc(fd.path, gfid=fd.gfid),
                                       xattrs, flags, xdata)
        return await self.children[0].fsetxattr(fd, xattrs, flags,
                                                xdata)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        if self._file_level() and XA_STATE in xattrs:
            # manual retention adjust: enterprise mode only extends
            try:
                cur = await self.children[0].getxattr(loc, XA_STATE)
                old = json.loads(bytes(cur[XA_STATE]))
                new = json.loads(bytes(xattrs[XA_STATE]))
                if self.opts["retention-mode"] == "enterprise" and \
                        new.get("start", 0) + new.get("period", 0) < \
                        old.get("start", 0) + old.get("period", 0):
                    raise FopError(errno.EPERM,
                                   "worm: enterprise retention may "
                                   "only extend")
            except (FopError, ValueError, KeyError) as e:
                if isinstance(e, FopError) and e.err == errno.EPERM:
                    raise
        return await self.children[0].setxattr(loc, xattrs, flags, xdata)
