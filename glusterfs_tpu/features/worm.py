"""features/worm — write-once-read-many enforcement.

Reference: xlators/features/read-only/worm.c: files may be created and
written once; after that, overwrites/truncates/unlinks are denied with
EROFS.  Appends (writes at EOF) are allowed, matching the reference's
O_APPEND carve-out."""

from __future__ import annotations

import errno

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option


@register("features/worm")
class WormLayer(Layer):
    OPTIONS = (
        Option("worm", "bool", default="on"),
    )

    def _on(self) -> bool:
        return bool(self.opts["worm"])

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        if self._on():
            ia = await self.children[0].fstat(fd)
            if offset < ia.size:
                raise FopError(errno.EROFS, "worm: overwrite denied")
        return await self.children[0].writev(fd, data, offset, xdata)

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        if self._on():
            raise FopError(errno.EROFS, "worm: truncate denied")
        return await self.children[0].truncate(loc, size, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        if self._on():
            raise FopError(errno.EROFS, "worm: truncate denied")
        return await self.children[0].ftruncate(fd, size, xdata)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        if self._on():
            raise FopError(errno.EROFS, "worm: unlink denied")
        return await self.children[0].unlink(loc, xdata)

    async def rename(self, oldloc: Loc, newloc: Loc,
                     xdata: dict | None = None):
        if self._on():
            raise FopError(errno.EROFS, "worm: rename denied")
        return await self.children[0].rename(oldloc, newloc, xdata)
