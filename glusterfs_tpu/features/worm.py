"""features/worm — write-once-read-many enforcement.

Reference: xlators/features/read-only/worm.c.  Two modes:

* volume-level (``worm on``): files may be created and written once;
  overwrites/truncates/unlinks deny with EROFS.
* file-level (``worm-file-level on``, worm.c worm_state_transition):
  a file left unmodified for ``auto-commit-period`` transitions to a
  RETAINED state (persisted in a ``trusted.worm.state`` xattr holding
  {start, period}); retained files deny every mutation until
  ``start + period`` passes, after which ``worm-files-deletable``
  decides whether unlink (alone) is allowed.  ``retention-mode``
  enterprise refuses to shorten a live retention; relax allows it.
"""

from __future__ import annotations

import errno
import json
import time

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option

XA_STATE = "trusted.worm.state"


@register("features/worm")
class WormLayer(Layer):
    OPTIONS = (
        Option("worm", "bool", default="on"),
        Option("worm-file-level", "bool", default="off",
               description="per-file WORM with retention (worm.c "
                           "worm_state_transition) instead of the "
                           "whole-volume write-once gate"),
        Option("worm-files-deletable", "bool", default="on",
               description="expired-retention files may be unlinked "
                           "(features.worm-files-deletable)"),
        Option("default-retention-period", "time", default="120",
               description="retention seconds stamped at the WORM "
                           "transition (features.default-retention-"
                           "period)"),
        Option("auto-commit-period", "time", default="180",
               description="idle seconds after the last modification "
                           "before a file turns WORM "
                           "(features.auto-commit-period)"),
        Option("retention-mode", "enum", default="relax",
               values=("relax", "enterprise"),
               description="enterprise: a live retention can only be "
                           "extended (features.retention-mode)"),
    )

    def _on(self) -> bool:
        return bool(self.opts["worm"]) and \
            not self.opts["worm-file-level"]

    def _file_level(self) -> bool:
        return bool(self.opts["worm-file-level"])

    async def _state(self, loc: Loc):
        """(retained, expired) after a lazy state transition."""
        try:
            x = await self.children[0].getxattr(loc, XA_STATE)
            st = json.loads(bytes(x[XA_STATE]))
        except (FopError, ValueError, KeyError):
            st = None
        now = time.time()
        if st is None:
            try:
                ia, _ = await self.children[0].lookup(loc)
            except FopError:
                return False, False
            if now - ia.mtime < self.opts["auto-commit-period"]:
                return False, False  # still in its commit window
            st = {"start": now,
                  "period": float(self.opts["default-retention-period"])}
            try:  # the lazy transition (worm_state_transition)
                await self.children[0].setxattr(
                    loc, {XA_STATE: json.dumps(st).encode()})
            except FopError:
                pass
        return True, now >= st["start"] + st["period"]

    async def _deny_file_level(self, loc: Loc, unlinking: bool = False):
        retained, expired = await self._state(loc)
        if not retained:
            return
        if unlinking and expired and self.opts["worm-files-deletable"]:
            return
        raise FopError(errno.EROFS, "worm: file retained")

    async def xorv(self, fd: FdObj, data, offset: int,
                   xdata: dict | None = None):
        # the parity-delta apply mutates stored bytes exactly like an
        # overwriting writev (read-xor-write is ALWAYS an overwrite):
        # the same retention fences must hold, or a delta wave's parity
        # half would slip past WORM while its data half is denied
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        elif self._on():
            raise FopError(errno.EROFS, "worm: overwrite denied")
        return await self.children[0].xorv(fd, data, offset, xdata)

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        elif self._on():
            ia = await self.children[0].fstat(fd)
            if offset < ia.size:
                raise FopError(errno.EROFS, "worm: overwrite denied")
        return await self.children[0].writev(fd, data, offset, xdata)

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(loc)
        elif self._on():
            raise FopError(errno.EROFS, "worm: truncate denied")
        return await self.children[0].truncate(loc, size, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(Loc(fd.path, gfid=fd.gfid))
        elif self._on():
            raise FopError(errno.EROFS, "worm: truncate denied")
        return await self.children[0].ftruncate(fd, size, xdata)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(loc, unlinking=True)
        elif self._on():
            raise FopError(errno.EROFS, "worm: unlink denied")
        return await self.children[0].unlink(loc, xdata)

    async def rename(self, oldloc: Loc, newloc: Loc,
                     xdata: dict | None = None):
        if self._file_level():
            await self._deny_file_level(oldloc)
        elif self._on():
            raise FopError(errno.EROFS, "worm: rename denied")
        return await self.children[0].rename(oldloc, newloc, xdata)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        if self._file_level() and XA_STATE in xattrs:
            # manual retention adjust: enterprise mode only extends
            try:
                cur = await self.children[0].getxattr(loc, XA_STATE)
                old = json.loads(bytes(cur[XA_STATE]))
                new = json.loads(bytes(xattrs[XA_STATE]))
                if self.opts["retention-mode"] == "enterprise" and \
                        new.get("start", 0) + new.get("period", 0) < \
                        old.get("start", 0) + old.get("period", 0):
                    raise FopError(errno.EPERM,
                                   "worm: enterprise retention may "
                                   "only extend")
            except (FopError, ValueError, KeyError) as e:
                if isinstance(e, FopError) and e.err == errno.EPERM:
                    raise
        return await self.children[0].setxattr(loc, xattrs, flags, xdata)
