"""features/read-only — reject all modifying fops with EROFS
(reference xlators/features/read-only/read-only.c)."""

from __future__ import annotations

import errno

from ..core.fops import WRITE_FOPS, FopError
from ..core.layer import Layer, register
from ..core.options import Option


@register("features/read-only")
class ReadOnlyLayer(Layer):
    OPTIONS = (
        Option("read-only", "bool", default="on"),
    )


def _rejecting(op_name: str):
    async def fop(self, *args, **kwargs):
        if self.opts["read-only"]:
            raise FopError(errno.EROFS, f"{op_name}: read-only volume")
        return await getattr(self.children[0], op_name)(*args, **kwargs)
    fop.__name__ = op_name
    return fop


for _f in WRITE_FOPS:
    setattr(ReadOnlyLayer, _f.value, _rejecting(_f.value))
