"""features/snapview — user-serviceable snapshots: the ``/.snaps``
virtual directory.

Reference: xlators/features/snapview-client + snapview-server: the
client half turns ``.snaps`` path components into virtual inodes; the
server half holds one gfapi instance per activated snapshot volume and
serves the real data out of it.  Here both halves live in one client
layer: ``/.snaps`` lists the volume's **activated** snapshots (mgmt
``snapshot-list``), and ``/.snaps/<snap>/<path>`` proxies read-class
fops into a lazily-created in-process mount of the snapshot's own
served volume (``snap-<name>``, spawned by ``snapshot activate`` — the
snapd analog).  Snapshots are history: every mutation under /.snaps is
EROFS (the snapshot volume's bricks are read-only anyway, belt and
braces)."""

from __future__ import annotations

import asyncio
import errno
import time

from ..core.fops import FopError
from ..core.iatt import Iatt
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core.virtfs import (install_readonly_guards, virtual_dir_iatt,
                           virtual_gfid)
from ..core import gflog

log = gflog.get_logger("snapview")

SNAPS = "/.snaps"


def _gfid(path: str) -> bytes:
    return virtual_gfid("snaps", path)


@register("features/snapview")
class SnapviewLayer(Layer):
    OPTIONS = (
        Option("mgmt-server", "str", default="127.0.0.1:24007",
               description="glusterd endpoint for snapshot-list and "
                           "snap volume volfiles"),
        Option("volume", "str", default="",
               description="parent volume whose snapshots to serve"),
        Option("refresh-interval", "time", default="2",
               description="snapshot-list cache lifetime"),
        Option("snapshot-directory", "str", default=".snaps",
               description="name of the snapshot entry directory "
                           "(features.snapshot-directory)"),
        Option("show-snapshot-directory", "bool", default="off",
               description="list the snapshot directory in readdir of "
                           "/ (features.show-snapshot-directory); off "
                           "keeps it enter-by-name only like the "
                           "reference default"),
    )

    def _snapdir(self) -> str:
        return "/" + str(self.opts["snapshot-directory"]).strip("/")

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._snaps: dict[str, dict] = {}
        self._snaps_at = 0.0
        self._mounts: dict[str, object] = {}  # snap -> in-process Client

    async def fini(self):
        for cl in self._mounts.values():
            try:
                await cl.unmount()
            except Exception:
                pass
        self._mounts.clear()
        await super().fini()

    # -- snapshot discovery / proxy mounts ---------------------------------

    def _mgmt(self):
        host, _, port = self.opts["mgmt-server"].partition(":")
        return host, int(port or 24007)

    async def _snapshots(self) -> dict[str, dict]:
        now = time.monotonic()
        if now - self._snaps_at > self.opts["refresh-interval"]:
            from ..mgmt.glusterd import MgmtClient

            host, port = self._mgmt()
            try:
                async with MgmtClient(host, port) as c:
                    out = await c.call("snapshot-list",
                                       volume=self.opts["volume"])
                self._snaps = {n: s for n, s in
                               out.get("snapshots", {}).items()
                               if s.get("activated")}
                self._snaps_at = now
                # a deactivated snapshot's cached proxy mount points at
                # killed brick ports: drop it (a reactivation respawns
                # on fresh ports)
                for gone in set(self._mounts) - set(self._snaps):
                    await self._drop_mount(gone)
            except Exception as e:
                log.debug(1, "snapshot-list failed: %r", e)
        return self._snaps

    async def _drop_mount(self, snap: str) -> None:
        cl = self._mounts.pop(snap, None)
        if cl is not None:
            try:
                await cl.unmount()
            except Exception:
                pass

    async def _snap_client(self, snap: str):
        cl = self._mounts.get(snap)
        if cl is not None:
            from ..protocol.client import ClientLayer
            from ..core.layer import walk as _walk

            subs = [l for l in _walk(cl.graph.top)
                    if isinstance(l, ClientLayer)]
            if subs and all(l.connected for l in subs):
                return cl
            # stale (deactivate/reactivate cycle): rebuild on the
            # snapshot volume's current ports
            await self._drop_mount(snap)
        from ..mgmt.glusterd import mount_volume

        host, port = self._mgmt()
        cl = await mount_volume(host, port, f"snap-{snap}")
        self._mounts[snap] = cl
        return cl

    # -- path splitting ----------------------------------------------------

    def _split(self, path: str | None):
        """None if not under the snap dir, else (snap|None, inner)."""
        SNAPS = self._snapdir()
        if not path or not (path == SNAPS or
                            path.startswith(SNAPS + "/")):
            return None
        rest = path[len(SNAPS):].lstrip("/")
        if not rest:
            return ("", "/")
        snap, _, inner = rest.partition("/")
        return (snap, "/" + inner)

    def _root_iatt(self, path: str) -> Iatt:
        return virtual_dir_iatt(_gfid(path))

    def _virt_loc(self, loc: Loc) -> bool:
        return self._split(loc.path) is not None

    def _virt_fd(self, fd: FdObj) -> bool:
        return fd.ctx_get(self) is not None or \
            self._split(fd.path) is not None

    async def _proxy(self, snap: str, op: str, inner_first, *rest):
        snaps = await self._snapshots()
        if snap not in snaps:
            raise FopError(errno.ENOENT,
                           f"{self._snapdir()}/{snap}")
        cl = await self._snap_client(snap)
        return await getattr(cl.graph.top, op)(inner_first, *rest)

    # -- fops --------------------------------------------------------------

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        sp = self._split(loc.path)
        if sp is None:
            return await self.children[0].lookup(loc, xdata)
        snap, inner = sp
        if not snap or inner == "/":
            if snap and snap not in await self._snapshots():
                raise FopError(errno.ENOENT, loc.path)
            return self._root_iatt(loc.path), {}
        return await self._proxy(snap, "lookup", Loc(inner), xdata)

    async def stat(self, loc: Loc, xdata: dict | None = None):
        sp = self._split(loc.path)
        if sp is None:
            return await self.children[0].stat(loc, xdata)
        snap, inner = sp
        if not snap or inner == "/":
            if snap and snap not in await self._snapshots():
                raise FopError(errno.ENOENT, loc.path)
            return self._root_iatt(loc.path)
        return await self._proxy(snap, "stat", Loc(inner), xdata)

    async def open(self, loc: Loc, flags: int = 0,
                   xdata: dict | None = None):
        sp = self._split(loc.path)
        if sp is None:
            return await self.children[0].open(loc, flags, xdata)
        snap, inner = sp
        if not snap or inner == "/":
            raise FopError(errno.EISDIR, loc.path)
        import os as _os

        if flags & (_os.O_WRONLY | _os.O_RDWR):
            raise FopError(errno.EROFS, "snapshots are read-only")
        fd = await self._proxy(snap, "open", Loc(inner), flags, xdata)
        wrapped = FdObj(fd.gfid, flags, path=loc.path)
        wrapped.ctx_set(self, (snap, fd))
        return wrapped

    def _inner_fd(self, fd: FdObj):
        ctx = fd.ctx_get(self)
        if ctx is None:
            return None
        return ctx

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        ctx = self._inner_fd(fd)
        if ctx is None:
            return await self.children[0].readv(fd, size, offset, xdata)
        snap, inner = ctx
        return await self._proxy(snap, "readv", inner, size, offset,
                                 xdata)

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        ctx = self._inner_fd(fd)
        if ctx is None:
            return await self.children[0].fstat(fd, xdata)
        snap, inner = ctx
        return await self._proxy(snap, "fstat", inner, xdata)

    async def release(self, fd: FdObj) -> None:
        ctx = fd.ctx_del(self)
        if ctx is None:
            await super().release(fd)
            return
        snap, inner = ctx
        cl = self._mounts.get(snap)
        if cl is not None:
            try:
                await cl.graph.top.release(inner)
            except Exception:
                pass

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        if self._inner_fd(fd) is not None:
            return {}
        return await self.children[0].flush(fd, xdata)

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Chains touching neither /.snaps paths nor snapshot fds
        forward intact (this layer is pure passthrough for the live
        volume); anything virtual decomposes so the read-only guards
        and snapshot proxies apply per fop."""
        from ..rpc import compound as cfop

        for _fop, args, kwargs in links:
            for a in list(args) + list((kwargs or {}).values()):
                if (isinstance(a, Loc) and self._split(a.path)
                        is not None) or \
                        (isinstance(a, FdObj)
                         and a.ctx_get(self) is not None):
                    return await cfop.decompose(self, links, xdata)
        return await self.children[0].compound(links, xdata)

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        sp = self._split(loc.path)
        if sp is None:
            return await self.children[0].opendir(loc, xdata)
        snap, inner = sp
        if not snap:
            return FdObj(_gfid(loc.path), path=loc.path)
        fd = await self._proxy(snap, "opendir", Loc(inner), xdata)
        wrapped = FdObj(fd.gfid, path=loc.path)
        wrapped.ctx_set(self, (snap, fd))
        return wrapped

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        ctx = self._inner_fd(fd)
        if ctx is None:
            if fd.path == self._snapdir():
                return [(n, None) for n in
                        sorted(await self._snapshots())]
            out = await self.children[0].readdir(fd, size, offset,
                                                 xdata)
            if fd.path == "/" and self.opts["show-snapshot-directory"]:
                # features.show-snapshot-directory: surface the entry
                # in / listings (default hidden, enter-by-name only)
                name = self._snapdir().lstrip("/")
                if all(n != name for n, _ in out):
                    out = list(out) + [(name, None)]
            return out
        snap, inner = ctx
        return await self._proxy(snap, "readdir", inner, size, offset,
                                 xdata)

    async def readdirp(self, fd: FdObj, size: int = 0, offset: int = 0,
                       xdata: dict | None = None):
        ctx = self._inner_fd(fd)
        if ctx is None:
            if fd.path == self._snapdir():
                return [(n, self._root_iatt(self._snapdir() + "/" + n))
                        for n in sorted(await self._snapshots())]
            return await self.children[0].readdirp(fd, size, offset,
                                                   xdata)
        snap, inner = ctx
        return await self._proxy(snap, "readdirp", inner, size, offset,
                                 xdata)

    async def readlink(self, loc: Loc, xdata: dict | None = None):
        sp = self._split(loc.path)
        if sp is None:
            return await self.children[0].readlink(loc, xdata)
        snap, inner = sp
        return await self._proxy(snap, "readlink", Loc(inner), xdata)

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        sp = self._split(loc.path)
        if sp is None:
            return await self.children[0].getxattr(loc, name, xdata)
        snap, inner = sp
        if not snap or inner == "/":
            return {}
        return await self._proxy(snap, "getxattr", Loc(inner), name,
                                 xdata)

    async def seek(self, fd: FdObj, offset: int, what: str = "data",
                   xdata: dict | None = None):
        ctx = self._inner_fd(fd)
        if ctx is None:
            return await self.children[0].seek(fd, offset, what, xdata)
        snap, inner = ctx
        return await self._proxy(snap, "seek", inner, offset, what,
                                 xdata)

    async def fsync(self, fd: FdObj, datasync: int = 0,
                    xdata: dict | None = None):
        if self._inner_fd(fd) is not None:
            return {}  # snapshots are immutable; nothing to sync
        return await self.children[0].fsync(fd, datasync, xdata)

    def dump_private(self) -> dict:
        return {"volume": self.opts["volume"],
                "snapshots": sorted(self._snaps),
                "mounted": sorted(self._mounts)}


install_readonly_guards(SnapviewLayer, "_virt_loc", "_virt_fd",
                        "snapshots are read-only")
