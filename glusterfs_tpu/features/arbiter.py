"""features/arbiter — metadata-only third replica.

Reference: xlators/features/arbiter (arbiter.c): the last brick of an
arbiter replica-3 group stores every file's *metadata* (entry, gfid,
afr xattrs) but no data — it exists to witness transactions so a
2-data-brick volume cannot split-brain.  The brick-side layer makes
that true mechanically:

* ``writev`` succeeds without touching data (file length on the brick
  stays 0; the fop still flows through locks/index/xattrop so version
  and pending accounting are identical to a data brick);
* data reads fail EINVAL (arbiter_readv) — the client never elects an
  arbiter for reads;
* truncate-class fops succeed as metadata no-ops.

The client half lives in cluster/afr: ``arbiter-count`` excludes the
group's last brick from read candidates, data heal, and size/policy
decisions.
"""

from __future__ import annotations

import errno

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option


@register("features/arbiter")
class ArbiterLayer(Layer):
    OPTIONS = (
        Option("arbiter", "bool", default="on"),
    )

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        """Ack the full write, store nothing (arbiter_writev returns
        iov_length without winding the data)."""
        ia = await self.children[0].fstat(fd)
        ia.size = 0
        return ia

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        raise FopError(errno.EINVAL, "arbiter holds no data")

    async def truncate(self, loc: Loc, size: int,
                       xdata: dict | None = None):
        return await self.children[0].stat(loc)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        return await self.children[0].fstat(fd)

    async def fallocate(self, fd: FdObj, mode: int, offset: int,
                        length: int, xdata: dict | None = None):
        return await self.children[0].fstat(fd)

    async def discard(self, fd: FdObj, offset: int, length: int,
                      xdata: dict | None = None):
        return await self.children[0].fstat(fd)

    async def zerofill(self, fd: FdObj, offset: int, length: int,
                       xdata: dict | None = None):
        return await self.children[0].fstat(fd)

    async def seek(self, fd: FdObj, offset: int, what: str = "data",
                   xdata: dict | None = None):
        raise FopError(errno.EINVAL, "arbiter holds no data")
