"""features/changelog — brick-side journal of mutating fops.

Reference: xlators/features/changelog (changelog.c, changelog-helpers.c):
every successful entry/data/metadata mutation appends a record to the
active CHANGELOG file, which rolls over every ``rollover-time`` seconds;
geo-replication's gsyncd consumes the rotated journals to discover what
changed without crawling (geo-replication/syncdaemon/primary.py:90-135).

TPU-build mechanisms: records are JSON lines (binary-safe via the hex
gfid; paths are JSON-escaped) written to numbered segments
``<dir>/CHANGELOG.<seq>`` — a new segment starts at rollover and at
layer init, and consumers tail (segment, offset) cursors, so rotation
never renames anything out from under a reader.  Record classes mirror
the reference: E (namespace), D (data), M (metadata).  Internal
accounting xattrs (trusted.ec.*, trusted.afr.*, glusterfs_tpu.*) are
not journaled.
"""

from __future__ import annotations

import json
import os
import time

from ..core.fops import Fop
from ..core.layer import FdObj, Layer, Loc, register, walk
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("changelog")

# fop -> record class (changelog-misc.h E/D/M split)
E_FOPS = {Fop.CREATE, Fop.MKNOD, Fop.MKDIR, Fop.UNLINK, Fop.RMDIR,
          Fop.SYMLINK, Fop.RENAME, Fop.LINK, Fop.ICREATE, Fop.PUT,
          # namelink is icreate's other half (gfid-access: link a name
          # to an existing inode) — an entry mutation like link;
          # graft-lint GL01 caught it journaling nowhere, which would
          # hide the new name from geo-rep forever
          Fop.NAMELINK}
D_FOPS = {Fop.WRITEV, Fop.TRUNCATE, Fop.FTRUNCATE, Fop.FALLOCATE,
          Fop.DISCARD, Fop.ZEROFILL, Fop.COPY_FILE_RANGE, Fop.PUT,
          # a parity-delta apply mutates data: journal it wherever it
          # lands (volgen additionally disables delta-writes under a
          # changelog-armed disperse graph — the UNTOUCHED data bricks
          # of a delta wave see no fop at all, which would starve a
          # geo-rep Active worker tailing one of them)
          Fop.XORV}
M_FOPS = {Fop.SETATTR, Fop.FSETATTR, Fop.SETXATTR, Fop.FSETXATTR,
          Fop.REMOVEXATTR, Fop.FREMOVEXATTR}

_INTERNAL_NS = ("trusted.ec.", "trusted.afr.", "trusted.bit-rot.",
                "glusterfs_tpu.")


@register("features/changelog")
class ChangelogLayer(Layer):
    OPTIONS = (
        Option("changelog", "bool", default="on"),
        Option("changelog-dir", "path", default="",
               description="journal directory (default: "
                           "<posix-root>/.glusterfs_tpu/changelog)"),
        Option("rollover-time", "time", default="15",
               description="start a new journal segment after this"),
        Option("fsync-interval", "time", default="5",
               description="fsync the live journal segment at most "
                           "this often (changelog.fsync-interval; 0 = "
                           "never — page cache only)"),
        Option("capture-del-path", "bool", default="on",
               description="record the full path on unlink records "
                           "(changelog.capture-del-path).  The "
                           "reference defaults off and has geo-rep/"
                           "glusterfind resolve deletes through their "
                           "gfid database; THIS build's consumers "
                           "replay deletes by path, so the default is "
                           "on — turning it off trades journal bytes "
                           "for gfid-only delete records"),
        Option("encoding", "enum", default="ascii",
               values=("ascii", "binary"),
               description="journal record encoding "
                           "(changelog.encoding): ascii = one JSON "
                           "object per line; binary = length-prefixed "
                           "compact records"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._dir: str | None = None
        self._seq = 0
        self._fh = None
        self._opened_at = 0.0
        self._start_ts = 0.0
        self.records = 0

    async def init(self):
        base = self.opts.get("changelog-dir")
        if not base:
            posix = next((l for l in walk(self)
                          if l.type_name == "storage/posix"), None)
            if posix is None:
                raise ValueError(f"{self.name}: no changelog-dir and no "
                                 f"storage/posix descendant")
            base = os.path.join(posix.root, ".glusterfs_tpu", "changelog")
        self._dir = os.path.abspath(base)
        os.makedirs(self._dir, exist_ok=True)
        self._seq = max((int(n.rsplit(".", 1)[1])
                         for n in os.listdir(self._dir)
                         if n.startswith("CHANGELOG.")), default=0)
        # journal coverage epoch (the HTIME marker analog): history
        # queries report it so a consumer asking about a window that
        # predates the journal knows to fall back to a namespace crawl
        htime = os.path.join(self._dir, "HTIME")
        if not os.path.exists(htime):
            with open(htime, "w") as f:
                f.write(repr(time.time()))
        with open(htime) as f:
            self._start_ts = float(f.read().strip() or 0)
        self._roll()  # fresh segment per process lifetime
        await super().init()

    # -- history API (gf-history-changelog.c + changelog-rpc.c: a
    # bounded time-window query served to consumers over the brick's
    # RPC — a remote glusterfind/gsyncd can follow a brick it can only
    # reach over the wire) --------------------------------------------

    async def changelog_history(self, since: float, until: float,
                                max_records: int = 100000) -> dict:
        """Records with since < ts <= until, time-ordered, capped at
        ``max_records`` (``truncated`` tells the consumer to re-query
        from the last record's ts).  ``start_ts`` is the journal's
        coverage epoch — a ``since`` before it means the window is NOT
        fully covered by changelogs (changelog_history() in the
        reference returns ENOENT for such windows)."""

        # runs on a to_thread worker; self._dir is safe to read there
        # because it is immutable after init() — a declared graft-race
        # ownership row (tables.OWNERSHIP["...ChangelogLayer._dir"])
        def scan():
            recs: list[dict] = []
            truncated = False
            names = sorted(
                (n for n in os.listdir(self._dir)
                 if n.startswith("CHANGELOG.")),
                key=lambda n: int(n.rsplit(".", 1)[1]))
            for name in names:
                try:
                    with open(os.path.join(self._dir, name)) as f:
                        for line in f:
                            try:
                                r = json.loads(line)
                            except ValueError:
                                continue
                            if since < r.get("ts", 0) <= until:
                                if len(recs) >= max_records:
                                    truncated = True
                                    break
                                recs.append(r)
                except OSError:
                    continue
                if truncated:
                    break
            recs.sort(key=lambda r: r.get("ts", 0))
            return recs, truncated

        import asyncio

        recs, truncated = await asyncio.to_thread(scan)
        return {"start_ts": self._start_ts, "records": recs,
                "truncated": truncated}

    async def fini(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        await super().fini()

    def _roll(self) -> None:
        if self._fh is not None:
            self._fh.close()
        self._seq += 1
        self._fh = open(os.path.join(self._dir, f"CHANGELOG.{self._seq}"),
                        "a", buffering=1)
        self._opened_at = time.monotonic()

    def _record(self, rtype: str, op: str, gfid: bytes | None,
                path: str, path2: str = "") -> None:
        if not self.opts["changelog"] or self._fh is None:
            return
        if time.monotonic() - self._opened_at > self.opts["rollover-time"]:
            self._roll()
        if op == "unlink" and not self.opts["capture-del-path"]:
            # reference default: deletes record the gfid only — the
            # path may already be reused by an unrelated file when a
            # consumer replays the journal (changelog.capture-del-path)
            path, path2 = "", ""
        rec = {"ts": time.time(), "type": rtype, "op": op,
               "gfid": gfid.hex() if gfid else "", "path": path}
        if path2:
            rec["path2"] = path2
        try:
            if self.opts["encoding"] == "binary":
                # compact separator-free records (~25% smaller
                # journals); both encodings stay line-framed so the
                # history scanner reads either
                self._fh.write(json.dumps(rec, separators=(",", ":"))
                               + "\n")
            else:
                self._fh.write(json.dumps(rec) + "\n")
            self.records += 1
            fsi = float(self.opts["fsync-interval"])
            now = time.monotonic()
            if fsi > 0 and now - getattr(self, "_last_fsync", 0) >= fsi:
                self._last_fsync = now
                os.fsync(self._fh.fileno())
        except OSError as e:
            log.error(1, "%s: journal write failed: %s", self.name, e)

    def dump_private(self) -> dict:
        return {"dir": self._dir, "segment": self._seq,
                "records": self.records,
                "enabled": self.opts["changelog"]}


def _journaled(fop: Fop, rtype: str):
    name = fop.value

    async def impl(self, *args, **kwargs):
        ret = await getattr(self.children[0], name)(*args, **kwargs)
        path, path2, gfid = "", "", None
        for a in args:
            if isinstance(a, Loc):
                if not path:
                    path, gfid = a.path, a.gfid
                else:
                    path2 = a.path
            elif isinstance(a, FdObj) and not path:
                path, gfid = a.path, a.gfid
            elif rtype == "M":
                # metadata touching only internal xattr namespaces is
                # cluster accounting, not user metadata — don't journal
                if isinstance(a, dict):
                    keys = [k for k in a if isinstance(k, str)]
                    if keys and all(k.startswith(_INTERNAL_NS)
                                    for k in keys):
                        return ret
                elif isinstance(a, str) and a.startswith(_INTERNAL_NS):
                    return ret
        from ..core.iatt import Iatt

        if gfid is None:
            if isinstance(ret, Iatt):
                gfid = ret.gfid
            elif isinstance(ret, tuple):
                for r in ret:
                    if isinstance(r, Iatt):
                        gfid = r.gfid
                        break
        self._record(rtype, name, gfid, path, path2)
        return ret

    impl.__name__ = name
    return impl


for _f in E_FOPS:
    setattr(ChangelogLayer, _f.value, _journaled(_f, "E"))
for _f in D_FOPS - E_FOPS:
    setattr(ChangelogLayer, _f.value, _journaled(_f, "D"))
for _f in M_FOPS:
    setattr(ChangelogLayer, _f.value, _journaled(_f, "M"))
