"""features/leases — NFS-style lease grants, recalls, and the
reader-interest commit push.

Reference: xlators/features/leases (leases.c): a client may take a
RD/RW lease on an inode; a conflicting fop from ANOTHER client recalls
the lease (upcall to the holder) and blocks for the recall timeout; an
unreturned lease is revoked.  Brick-side layer: leases are keyed by
gfid and (client, lease-id), conflict checks gate the write path,
recalls ride the same event-push channel the upcall layer uses.

The lease contract here (ISSUE 16) is what lets the client-side caches
(md-cache/quick-read/io-cache, the gateway object cache) serve hits
with ZERO wire fops: while a lease is held, no TTL revalidation runs —
coherence is recall-exact, not timeout-approximate.  Three obligations
make that sound:

* **Recall before conflict.**  Any conflicting write-class fop recalls
  holders through the upcall sink and WAITS (bounded by
  ``recall-timeout``) before proceeding; an unreturned lease is
  revoked and its (client, lease-id) poisoned, so a holder that went
  quiet can never ride a stale grant back in.
* **Grant waits out open write windows.**  A read-lease grant is the
  reader's registered interest: if another client holds an inodelk on
  the gfid (an EC/AFR eager window with a pending delayed post-op),
  the grant pushes ``inodelk-contention`` at the holders via the
  sibling locks layer and waits for the locks to clear — the pending
  eager post-op COMMITS before the grant returns, closing the
  cross-door read-after-PUT window PR 6 documented.
* **Reap on disconnect.**  ``release_client`` (the client_t reap path)
  drops a dead holder's leases, so a crashed client stalls writers for
  at most one recall-timeout, never forever.

Leases idle past ``lease-timeout`` expire (amortized sweep); the
holder is told via the same ``lease-recall`` event so its caches drop.
"""

from __future__ import annotations

import asyncio
import errno
import time
from typing import Callable

from ..core.fops import FopError, WRITE_FOPS
from ..core.layer import FdObj, Layer, Loc, register, walk
from ..core.options import Option
from ..core import gflog
from ..core.events import gf_event
from ..core.metrics import REGISTRY
from ..rpc import wire

log = gflog.get_logger("leases")

RD_LEASE, RW_LEASE = "rd", "rw"

#: recall poll period while waiting out a recall / an open write window
_POLL = 0.02


class _Lease:
    __slots__ = ("lease_id", "ltype", "client", "granted_at",
                 "recalled_at")

    def __init__(self, lease_id: str, ltype: str, client: bytes):
        self.lease_id = lease_id
        self.ltype = ltype
        self.client = client
        self.granted_at = time.monotonic()
        self.recalled_at = 0.0


@register("features/leases")
class LeasesLayer(Layer):
    OPTIONS = (
        Option("leases", "bool", default="on"),
        Option("recall-timeout", "time", default="2",
               description="grace before an unreturned lease is "
                           "revoked (lease-lock-recall-timeout)"),
        Option("lease-timeout", "time", default="600", min=0,
               description="idle expiry: a lease not renewed (by the "
                           "holder's reads or a repeat grant) for this "
                           "long is dropped and the holder told "
                           "(features.lease-timeout); 0 = never"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._leases: dict[bytes, list[_Lease]] = {}  # gfid -> leases
        self._sink: Callable | None = None
        # revocations are per (client, lease-id) — one client's
        # revoked id must not poison everyone else's
        self._revoked: set[tuple[bytes, str]] = set()
        # recall/drop accounting by reason (the
        # gftpu_lease_recalls_total family): conflict = a conflicting
        # fop recalled holders; revoked = the recall grace expired;
        # expired = idle past lease-timeout; disconnect = client_t reap
        self.recalls: dict[str, int] = {"conflict": 0, "revoked": 0,
                                        "expired": 0, "disconnect": 0}
        self._ops = 0  # amortized-sweep counter
        self._locks = None  # sibling locks layer (resolved lazily)
        _LIVE_LEASES.add(self)

    def set_upcall_sink(self, sink) -> None:
        self._sink = sink

    def release_client(self, identity: bytes) -> None:
        """Disconnect reap (client_t cleanup, PR 9's release_client
        walk): a dead holder's leases must not stall writers for more
        than the one recall-timeout already in flight."""
        self._revoked = {(c, i) for c, i in self._revoked
                         if c != identity}
        for gfid in list(self._leases):
            kept = [l for l in self._leases[gfid]
                    if l.client != identity]
            dropped = len(self._leases[gfid]) - len(kept)
            if dropped:
                self.recalls["disconnect"] += dropped
            if kept:
                self._leases[gfid] = kept
            else:
                del self._leases[gfid]

    # -- expiry sweep (amortized like upcall's registry sweep) -------------

    def _expire(self) -> None:
        timeout = self.opts["lease-timeout"]
        if not timeout:
            return
        horizon = time.monotonic() - timeout
        for gfid in list(self._leases):
            held = self._leases[gfid]
            dead = [l for l in held if l.granted_at < horizon]
            if not dead:
                continue
            kept = [l for l in held if l not in dead]
            if kept:
                self._leases[gfid] = kept
            else:
                del self._leases[gfid]
            self.recalls["expired"] += len(dead)
            for l in dead:
                # tell the holder: its zero-RT cache mode must end (the
                # recall event doubles as the expiry notice — the
                # client drops cached state exactly as on a recall)
                if self._sink is not None:
                    self._sink([l.client],
                               {"event": "lease-recall", "gfid": gfid,
                                "lease-id": l.lease_id,
                                "reason": "expired"})
                gf_event("LEASE_EXPIRED", gfid=gfid.hex(),
                         lease_id=l.lease_id, ltype=l.ltype,
                         brick=self.name)

    def _tick(self) -> None:
        self._ops += 1
        if self._ops % 1024 == 0:
            self._expire()

    # -- the sibling locks layer (reader-interest commit push) -------------

    def _locks_layer(self):
        """The locks layer below us, if any — the grant path asks it
        which OTHER clients hold inodelks on the gfid (an open eager
        window) and nudges them to commit."""
        if self._locks is None:
            self._locks = next(
                (l for l in walk(self) if l is not self
                 and hasattr(l, "contend_gfid")), False)
        return self._locks or None

    async def _settle_windows(self, gfid: bytes, client: bytes) -> None:
        """The reader's registered interest PUSHES any pending eager
        post-op: fire inodelk-contention at every other client holding
        an inodelk on this gfid (their EC/AFR drains the window and
        commits the delayed post-op NOW), then wait — bounded by
        recall-timeout — for the locks to clear.  After this returns
        quiet, a lookup votes the committed size: the cross-door
        read-after-PUT window is closed, not documented."""
        locks = self._locks_layer()
        if locks is None:
            return
        holders = locks.inodelk_holders(gfid, but_not=client)
        if not holders:
            return
        locks.contend_gfid(gfid, but_not=client)
        deadline = time.monotonic() + self.opts["recall-timeout"]
        while time.monotonic() < deadline:
            await asyncio.sleep(_POLL)
            if not locks.inodelk_holders(gfid, but_not=client):
                return
        # an unresponsive writer must not wedge reads forever: grant
        # anyway after the grace (the same stance the revocation plane
        # takes on wedged locks) — the window commits on its own timer
        log.warning(3, "%s: eager-window holders on %s ignored the "
                    "grant nudge for %.1fs", self.name, gfid.hex(),
                    self.opts["recall-timeout"])

    # -- the lease fop (GF_FOP_LEASE) --------------------------------------

    async def lease(self, loc: Loc, cmd: str, ltype: str = RD_LEASE,
                    lease_id: str = "", xdata: dict | None = None):
        """cmd: grant | release | unlock-all."""
        if not self.opts["leases"]:
            raise FopError(errno.ENOTSUP, "leases disabled")
        client = wire.CURRENT_CLIENT.get()
        if loc.gfid:
            gfid = bytes(loc.gfid)
        else:
            ia, _ = await self.children[0].lookup(loc)
            gfid = bytes(ia.gfid)
        self._tick()
        held = self._leases.get(gfid, [])
        if cmd == "grant":
            if not lease_id:
                raise FopError(errno.EINVAL, "grant needs a lease-id")
            if (client, lease_id) in self._revoked:
                raise FopError(errno.ESTALE, "lease was revoked")
            # a RW lease conflicts with anything from another client;
            # RD leases share with RD.  Only a SUCCESSFUL grant may
            # materialize the gfid entry (failed probes must not grow
            # the table).
            for l in held:
                if l.client != client and (ltype == RW_LEASE or
                                           l.ltype == RW_LEASE):
                    raise FopError(errno.EAGAIN,
                                   "conflicting lease held")
            prior = next((l for l in held if l.client == client
                          and l.lease_id == lease_id), None)
            if prior is not None:
                # repeat grant = renewal: refresh the expiry stamp and
                # upgrade rd -> rw in place
                prior.granted_at = time.monotonic()
                if ltype == RW_LEASE:
                    prior.ltype = RW_LEASE
            else:
                self._leases.setdefault(gfid, []).append(
                    _Lease(lease_id, ltype, client))
                gf_event("LEASE_GRANTED", gfid=gfid.hex(),
                         lease_id=lease_id, ltype=ltype,
                         brick=self.name)
            # the grant IS the reader's registered interest: settle any
            # open write window before the caller starts trusting its
            # cache (see _settle_windows)
            await self._settle_windows(gfid, client)
            return {"granted": ltype, "lease-id": lease_id}
        if cmd == "release":
            before = len(held)
            held[:] = [l for l in held if not (
                l.client == client and l.lease_id == lease_id)]
            if not held:
                self._leases.pop(gfid, None)
            return {"released": before - len(held)}
        if cmd == "unlock-all":
            self.release_client(client)
            return {"released": "all"}
        raise FopError(errno.EINVAL, f"lease cmd {cmd!r}")

    # -- the conflict gate --------------------------------------------------

    async def _check(self, gfid: bytes, is_write: bool) -> None:
        """Conflict gate: recall other clients' conflicting leases and
        wait out the grace, then revoke (lease_recall + timeout).  A
        voluntarily returned lease (the holder's release ack arrives
        AFTER it dropped its cached state) ends the wait early — the
        conflicting fop proceeds only once no holder can serve a stale
        hit."""
        client = wire.CURRENT_CLIENT.get()
        self._tick()
        held = self._leases.get(gfid, [])
        conflicting = [l for l in held if l.client != client and
                       (is_write or l.ltype == RW_LEASE)]
        if not conflicting:
            return
        now = time.monotonic()
        for l in conflicting:
            if not l.recalled_at:
                l.recalled_at = now
                self.recalls["conflict"] += 1
                gf_event("LEASE_RECALLED", gfid=gfid.hex(),
                         lease_id=l.lease_id, ltype=l.ltype,
                         brick=self.name)
                if self._sink is not None:
                    # raw-bytes gfid: the holder's md-cache/quick-read/
                    # io-cache invalidate on the same payload shape the
                    # upcall layer's cache-invalidation events carry
                    self._sink([l.client], {
                        "event": "lease-recall",
                        "gfid": gfid, "lease-id": l.lease_id,
                        "reason": "conflict"})
        deadline = max(l.recalled_at for l in conflicting) + \
            self.opts["recall-timeout"]
        while time.monotonic() < deadline:
            held = self._leases.get(gfid, [])
            if not any(l in held for l in conflicting):
                return  # returned voluntarily
            await asyncio.sleep(_POLL)
        # grace expired: revoke
        survivors = [l for l in conflicting
                     if l in self._leases.get(gfid, [])]
        for l in survivors:
            self._revoked.add((l.client, l.lease_id))
            self.recalls["revoked"] += 1
            gf_event("LEASE_REVOKED", gfid=gfid.hex(),
                     lease_id=l.lease_id, ltype=l.ltype,
                     brick=self.name)
        self._leases[gfid] = [l for l in self._leases.get(gfid, [])
                              if l not in conflicting]
        if not self._leases[gfid]:
            del self._leases[gfid]
        log.warning(1, "revoked %d unreturned lease(s) on %s",
                    len(survivors), gfid.hex())

    async def rename(self, oldloc: Loc, newloc: Loc,
                     xdata: dict | None = None):
        """Rename recalls BOTH ends: the source holder loses its name,
        and — the one the generic gate would miss — the DESTINATION
        holder is about to have its object replaced out from under it
        (the gateway's PUT commit is exactly this temp+rename shape).
        The destination loc usually arrives without a gfid (it names
        where the file WILL be), so the existing occupant is looked up
        brick-locally."""
        if self.opts["leases"]:
            if oldloc.gfid:
                await self._check(bytes(oldloc.gfid), True)
            dst = bytes(newloc.gfid) if newloc.gfid else None
            if dst is None:
                try:
                    ia, _ = await self.children[0].lookup(newloc)
                    dst = bytes(ia.gfid)
                except FopError:
                    dst = None  # fresh destination: nobody to recall
            if dst is not None:
                await self._check(dst, True)
        return await self.children[0].rename(oldloc, newloc, xdata)

    async def open(self, loc: Loc, flags: int = 0,
                   xdata: dict | None = None):
        import os as _os

        ret = await self.children[0].open(loc, flags, xdata)
        if self.opts["leases"] and loc.gfid:
            # write-opens conflict with any lease; read-opens conflict
            # with RW leases (leases.c open path)
            wr = bool(flags & (_os.O_WRONLY | _os.O_RDWR))
            await self._check(bytes(loc.gfid), wr)
        return ret

    async def readv(self, fd, size: int, offset: int,
                    xdata: dict | None = None):
        if self.opts["leases"] and fd.gfid:
            gfid = bytes(fd.gfid)
            # a reader must recall another client's RW lease first
            # (its holder may be caching unwritten data)
            await self._check(gfid, False)
            # the holder's own reads renew its lease (expiry is IDLE
            # expiry, not a hard deadline on an active holder)
            client = wire.CURRENT_CLIENT.get(None)
            if client is not None:
                now = time.monotonic()
                for l in self._leases.get(gfid, []):
                    if l.client == client:
                        l.granted_at = now
        return await self.children[0].readv(fd, size, offset, xdata)

    # -- introspection (the lease wedge view, beside PR 9's locks) ---------

    def lease_status(self) -> dict:
        """``volume status ... callpool`` share: held/recalling counts
        and the oldest holder's age, so a stuck recall is visible, not
        a mystery hang."""
        now = time.monotonic()
        held = recalling = 0
        oldest = 0.0
        by_type = {"rd": 0, "rw": 0}
        for leases in self._leases.values():
            for l in leases:
                if l.recalled_at:
                    recalling += 1
                else:
                    held += 1
                by_type[l.ltype] = by_type.get(l.ltype, 0) + 1
                oldest = max(oldest, now - l.granted_at)
        return {"held": held, "recalling": recalling,
                "by_type": by_type, "inodes": len(self._leases),
                "oldest_holder_age": round(oldest, 3),
                "recalls": dict(self.recalls)}

    def dump_private(self) -> dict:
        now = time.monotonic()
        table = []
        for gfid, leases in self._leases.items():
            for l in leases:
                table.append({
                    "gfid": gfid.hex(), "lease_id": l.lease_id[:16],
                    "client": l.client.hex() if l.client else "",
                    "type": l.ltype,
                    "age": round(now - l.granted_at, 3),
                    "recalling": bool(l.recalled_at),
                    "recall_age": round(now - l.recalled_at, 3)
                    if l.recalled_at else 0.0})
        return {"inodes": len(self._leases), "leases": len(table),
                "table": table, **self.lease_status()}


def _gated(op_name: str):
    async def impl(self, *args, **kwargs):
        if self.opts["leases"]:
            gfid = None
            for a in args:
                if isinstance(a, (Loc, FdObj)) and a.gfid:
                    gfid = bytes(a.gfid)
                    break
            if gfid:
                await self._check(gfid, True)
        return await getattr(self.children[0], op_name)(*args, **kwargs)
    impl.__name__ = op_name
    return impl


for _f in WRITE_FOPS:
    # lease is the plane's own fop; rename has a two-sided check above
    # that the single-gfid gate would clobber; xattrop/fxattrop are
    # internal transaction fops (EC/AFR pre/post-op version commits,
    # never issued by applications) — the reference's is_internal_fop
    # exemption, without which a read-lease grant would deadlock
    # against the very eager-window commit it pushes
    if _f.value not in ("lease", "rename", "xattrop", "fxattrop"):
        setattr(LeasesLayer, _f.value, _gated(_f.value))


# one family set scraped over every live leases layer (the
# register_objects weak-population pattern core/metrics documents)
_LIVE_LEASES = REGISTRY.register_objects(
    "gftpu_leases", "gauge",
    "brick lease tables by state (held = granted and quiet; "
    "recalling = a recall upcall is outstanding)",
    lambda l: [({"state": "held"}, l.lease_status()["held"]),
               ({"state": "recalling"}, l.lease_status()["recalling"])])
REGISTRY.register_objects(
    "gftpu_lease_recalls_total", "counter",
    "lease recalls/drops by reason (conflict = recall issued for a "
    "conflicting fop; revoked = recall grace expired; expired = idle "
    "past lease-timeout; disconnect = holder's client_t reaped)",
    lambda l: [({"reason": k}, v) for k, v in sorted(l.recalls.items())],
    live=_LIVE_LEASES)
