"""features/leases — NFS-style lease grants and recalls.

Reference: xlators/features/leases (leases.c): a client may take a
RD/RW lease on an inode; a conflicting fop from ANOTHER client recalls
the lease (upcall to the holder) and blocks for the recall timeout; an
unreturned lease is revoked.  Brick-side layer: leases are keyed by
gfid and lease-id, conflict checks gate the write path, recalls ride
the same event-push channel the upcall layer uses.
"""

from __future__ import annotations

import asyncio
import errno
import time
from typing import Callable

from ..core.fops import FopError, WRITE_FOPS
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog
from ..rpc import wire

log = gflog.get_logger("leases")

RD_LEASE, RW_LEASE = "rd", "rw"


class _Lease:
    __slots__ = ("lease_id", "ltype", "client", "recalled_at")

    def __init__(self, lease_id: str, ltype: str, client: bytes):
        self.lease_id = lease_id
        self.ltype = ltype
        self.client = client
        self.recalled_at = 0.0


@register("features/leases")
class LeasesLayer(Layer):
    OPTIONS = (
        Option("leases", "bool", default="on"),
        Option("recall-timeout", "time", default="2",
               description="grace before an unreturned lease is "
                           "revoked (lease-lock-recall-timeout)"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._leases: dict[bytes, list[_Lease]] = {}  # gfid -> leases
        self._sink: Callable | None = None
        # revocations are per (client, lease-id) — one client's
        # revoked id must not poison everyone else's
        self._revoked: set[tuple[bytes, str]] = set()

    def set_upcall_sink(self, sink) -> None:
        self._sink = sink

    def release_client(self, identity: bytes) -> None:
        self._revoked = {(c, i) for c, i in self._revoked
                         if c != identity}
        for gfid in list(self._leases):
            kept = [l for l in self._leases[gfid]
                    if l.client != identity]
            if kept:
                self._leases[gfid] = kept
            else:
                del self._leases[gfid]

    # -- the lease fop (GF_FOP_LEASE) --------------------------------------

    async def lease(self, loc: Loc, cmd: str, ltype: str = RD_LEASE,
                    lease_id: str = "", xdata: dict | None = None):
        """cmd: grant | release | unlock-all."""
        if not self.opts["leases"]:
            raise FopError(errno.ENOTSUP, "leases disabled")
        client = wire.CURRENT_CLIENT.get()
        ia, _ = await self.children[0].lookup(loc)
        gfid = bytes(ia.gfid)
        held = self._leases.get(gfid, [])
        if cmd == "grant":
            if not lease_id:
                raise FopError(errno.EINVAL, "grant needs a lease-id")
            if (client, lease_id) in self._revoked:
                raise FopError(errno.ESTALE, "lease was revoked")
            # a RW lease conflicts with anything from another client;
            # RD leases share with RD.  Only a SUCCESSFUL grant may
            # materialize the gfid entry (failed probes must not grow
            # the table).
            for l in held:
                if l.client != client and (ltype == RW_LEASE or
                                           l.ltype == RW_LEASE):
                    raise FopError(errno.EAGAIN,
                                   "conflicting lease held")
            self._leases.setdefault(gfid, []).append(
                _Lease(lease_id, ltype, client))
            return {"granted": ltype, "lease-id": lease_id}
        if cmd == "release":
            before = len(held)
            held[:] = [l for l in held if not (
                l.client == client and l.lease_id == lease_id)]
            if not held:
                self._leases.pop(gfid, None)
            return {"released": before - len(held)}
        if cmd == "unlock-all":
            self.release_client(client)
            return {"released": "all"}
        raise FopError(errno.EINVAL, f"lease cmd {cmd!r}")

    async def _check(self, gfid: bytes, is_write: bool) -> None:
        """Conflict gate: recall other clients' conflicting leases and
        wait out the grace, then revoke (lease_recall + timeout)."""
        client = wire.CURRENT_CLIENT.get()
        held = self._leases.get(gfid, [])
        conflicting = [l for l in held if l.client != client and
                       (is_write or l.ltype == RW_LEASE)]
        if not conflicting:
            return
        now = time.monotonic()
        for l in conflicting:
            if not l.recalled_at:
                l.recalled_at = now
                if self._sink is not None:
                    self._sink([l.client], {
                        "event": "lease-recall",
                        "gfid": gfid.hex(), "lease-id": l.lease_id})
        deadline = max(l.recalled_at for l in conflicting) + \
            self.opts["recall-timeout"]
        while time.monotonic() < deadline:
            held = self._leases.get(gfid, [])
            if not any(l in held for l in conflicting):
                return  # returned voluntarily
            await asyncio.sleep(0.05)
        # grace expired: revoke
        for l in conflicting:
            self._revoked.add((l.client, l.lease_id))
        self._leases[gfid] = [l for l in self._leases.get(gfid, [])
                              if l not in conflicting]
        log.warning(1, "revoked %d unreturned lease(s) on %s",
                    len(conflicting), gfid.hex())

    async def open(self, loc: Loc, flags: int = 0,
                   xdata: dict | None = None):
        import os as _os

        ret = await self.children[0].open(loc, flags, xdata)
        if self.opts["leases"] and loc.gfid:
            # write-opens conflict with any lease; read-opens conflict
            # with RW leases (leases.c open path)
            wr = bool(flags & (_os.O_WRONLY | _os.O_RDWR))
            await self._check(bytes(loc.gfid), wr)
        return ret

    async def readv(self, fd, size: int, offset: int,
                    xdata: dict | None = None):
        if self.opts["leases"] and fd.gfid:
            # a reader must recall another client's RW lease first
            # (its holder may be caching unwritten data)
            await self._check(bytes(fd.gfid), False)
        return await self.children[0].readv(fd, size, offset, xdata)

    def dump_private(self) -> dict:
        return {"inodes": len(self._leases),
                "leases": sum(len(v) for v in self._leases.values())}


def _gated(op_name: str):
    async def impl(self, *args, **kwargs):
        if self.opts["leases"]:
            gfid = None
            for a in args:
                if isinstance(a, (Loc, FdObj)) and a.gfid:
                    gfid = bytes(a.gfid)
                    break
            if gfid:
                await self._check(gfid, True)
        return await getattr(self.children[0], op_name)(*args, **kwargs)
    impl.__name__ = op_name
    return impl


for _f in WRITE_FOPS:
    if _f.value not in ("lease",):
        setattr(LeasesLayer, _f.value, _gated(_f.value))
