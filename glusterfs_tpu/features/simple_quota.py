"""features/simple-quota — lightweight namespace quota.

Reference: xlators/features/simple-quota (simple-quota.c).  Unlike the
full quota/marker/quotad triple, simple-quota scopes accounting to
*namespaces* — top-level directories — and keeps one delta-updated
usage counter per namespace:

* limit arrives as a setxattr of ``trusted.gfs.squota.limit`` on the
  namespace directory (simple-quota.c:905 sq_set_xattr path) and is
  persisted there;
* usage is updated in memory from write/truncate/unlink size deltas
  (sq_update_namespace, simple-quota.c:150) and lazily flushed to the
  namespace dir's ``trusted.gfs.squota.size`` xattr, re-seeded from it
  on init (sq_read_size, simple-quota.c:222);
* writes into a namespace over its hard limit fail EDQUOT
  (sq_writev's take_action path);
* ``glusterfs.quota.total-usage`` reads back usage+limit virtually
  (QUOTA_USAGE_KEY, simple-quota.c:19).

Accounting is approximate by design (the reference's stated tradeoff):
deltas, not crawls, so a brick that missed traffic re-seeds from the
persisted xattr rather than re-scanning.
"""

from __future__ import annotations

import errno
import json

from ..core.fops import FopError
from ..core.iatt import IAType
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("features.simple-quota")

XA_LIMIT = "trusted.gfs.squota.limit"
XA_SIZE = "trusted.gfs.squota.size"
V_USAGE = "glusterfs.quota.total-usage"


def _ns_of(path: str) -> str | None:
    """Namespace = first path component ('/a/b/c' -> '/a')."""
    parts = path.strip("/").split("/", 1)
    return f"/{parts[0]}" if parts and parts[0] else None


@register("features/simple-quota")
class SimpleQuotaLayer(Layer):
    OPTIONS = (
        Option("usage-scale", "int", default=1,
               description="backend->logical byte factor (K on a "
                           "disperse brick)"),
        Option("flush-interval", "time", default="2",
               description="seconds between usage xattr flushes"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.limits: dict[str, int] = {}   # ns dir -> bytes (logical)
        self._usage: dict[str, int] = {}   # ns dir -> backend bytes
        self._flushed: dict[str, float] = {}

    async def init(self) -> None:
        await super().init()
        # discover limited namespaces: scan top-level dirs once
        try:
            fd = await self.children[0].opendir(Loc("/"))
            entries = await self.children[0].readdir(fd)
        except FopError:
            return
        for e in entries:
            name = e[0] if isinstance(e, tuple) else e
            if name in (".", ".."):
                continue
            ns = f"/{name}"
            try:
                xa = await self.children[0].getxattr(Loc(ns)) or {}
            except FopError:
                continue
            if XA_LIMIT in xa:
                try:
                    self.limits[ns] = int(xa[XA_LIMIT])
                    self._usage[ns] = int(xa.get(XA_SIZE, 0))
                except (TypeError, ValueError):
                    pass

    # -- limit admin (xattr interface) -------------------------------------

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        if XA_LIMIT in xattrs:
            ns = loc.path.rstrip("/")
            if not ns or "/" in ns.lstrip("/"):
                raise FopError(errno.EINVAL,
                               "squota limit goes on a top-level "
                               "namespace directory")
            ia, _ = await self.children[0].lookup(loc)
            if ia.ia_type is not IAType.DIR:
                raise FopError(errno.ENOTDIR, loc.path)
            limit = int(xattrs[XA_LIMIT])
            if limit > 0:
                self.limits[ns] = limit
                self._usage.setdefault(ns, 0)
            else:  # limit 0/negative clears (QUOTA_RESET_KEY spirit)
                self.limits.pop(ns, None)
                self._usage.pop(ns, None)
        return await self.children[0].setxattr(loc, xattrs, flags, xdata)

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        if name == V_USAGE:
            # any path inside a namespace reports the enclosing
            # namespace's usage (limits only ever key top-level dirs)
            ns = _ns_of(loc.path)
            scale = self.opts["usage-scale"]
            if ns in self.limits:
                return {V_USAGE: json.dumps({
                    "used": self._usage.get(ns, 0) * scale,
                    "limit": self.limits[ns]}).encode()}
            raise FopError(errno.ENODATA, f"no squota on {ns}")
        return await self.children[0].getxattr(loc, name, xdata)

    # -- accounting + enforcement ------------------------------------------

    def _charge(self, path: str | None, delta: int) -> None:
        if not path or not delta:
            return
        ns = _ns_of(path)
        if ns in self.limits:
            self._usage[ns] = max(0, self._usage.get(ns, 0) + delta)

    def _enforce(self, path: str | None, want: int) -> None:
        ns = _ns_of(path or "")
        if ns is None or ns not in self.limits:
            return
        scale = self.opts["usage-scale"]
        if (self._usage.get(ns, 0) + want) * scale > self.limits[ns]:
            raise FopError(errno.EDQUOT,
                           f"{ns}: simple-quota limit "
                           f"{self.limits[ns]} exceeded")

    async def _flush(self, ns: str) -> None:
        import time as _t

        now = _t.monotonic()
        if now - self._flushed.get(ns, 0) < float(
                self.opts["flush-interval"]):
            return
        self._flushed[ns] = now
        try:
            await self.children[0].setxattr(
                Loc(ns), {XA_SIZE: str(self._usage.get(ns, 0)).encode()})
        except FopError:
            pass

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        path = getattr(fd, "path", None)
        grow = max(0, offset + len(data))  # worst case: all new bytes
        if path and _ns_of(path) in self.limits:
            ia = await self.children[0].fstat(fd)
            grow = max(0, offset + len(data) - ia.size)
            self._enforce(path, grow)
        out = await self.children[0].writev(fd, data, offset, xdata)
        if path and grow:
            self._charge(path, grow)
            ns = _ns_of(path)
            if ns in self.limits:
                await self._flush(ns)
        return out

    async def truncate(self, loc: Loc, size: int,
                       xdata: dict | None = None):
        ns = _ns_of(loc.path)
        old = None
        if ns in self.limits:
            ia, _ = await self.children[0].lookup(loc)
            old = ia.size
            self._enforce(loc.path, size - old)
        out = await self.children[0].truncate(loc, size, xdata)
        if old is not None:
            self._charge(loc.path, size - old)
            await self._flush(ns)
        return out

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        path = getattr(fd, "path", None)
        ns = _ns_of(path or "")
        old = None
        if path and ns in self.limits:
            ia = await self.children[0].fstat(fd)
            old = ia.size
            self._enforce(path, size - old)
        out = await self.children[0].ftruncate(fd, size, xdata)
        if old is not None:
            self._charge(path, size - old)
            await self._flush(ns)
        return out

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        ns = _ns_of(loc.path)
        freed = 0
        if ns in self.limits:
            try:
                ia, _ = await self.children[0].lookup(loc)
                freed = ia.size
            except FopError:
                pass
        out = await self.children[0].unlink(loc, xdata)
        if freed:
            self._charge(loc.path, -freed)
            await self._flush(ns)
        return out

    def dump_private(self) -> dict:
        return {"limits": dict(self.limits),
                "usage": dict(self._usage)}
