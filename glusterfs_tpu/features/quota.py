"""features/quota — directory usage limits.

Reference: xlators/features/quota (7k LoC; quota.c:635 quota_check_limit)
with marker-based contribution accounting.  Here: limits live in the
layer (set via ``limit_set``/options or the ``trusted.glusterfs.quota.
limit-set`` xattr); usage is computed on demand by walking the subtree
and then maintained incrementally by write/truncate/unlink deltas —
functionally the marker accounting without the persistent xattr climb."""

from __future__ import annotations

import errno

from ..core.fops import FopError
from ..core.iatt import IAType
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option

XA_LIMIT = "trusted.glusterfs.quota.limit-set"


@register("features/quota")
class QuotaLayer(Layer):
    OPTIONS = (
        Option("default-soft-limit", "percent", default=80.0),
        Option("hard-timeout", "time", default="5"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.limits: dict[str, int] = {}  # dir path -> bytes
        self._usage: dict[str, int] = {}  # dir path -> bytes (tracked)

    # -- admin API (quota CLI path) ----------------------------------------

    def limit_set(self, path: str, limit: int) -> None:
        self.limits[path.rstrip("/") or "/"] = limit
        self._usage.pop(path.rstrip("/") or "/", None)

    def limit_remove(self, path: str) -> None:
        self.limits.pop(path.rstrip("/") or "/", None)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        if XA_LIMIT in xattrs:
            self.limit_set(loc.path, int(xattrs[XA_LIMIT]))
            xattrs = {k: v for k, v in xattrs.items() if k != XA_LIMIT}
            if not xattrs:
                return {}
        return await self.children[0].setxattr(loc, xattrs, flags, xdata)

    # -- accounting --------------------------------------------------------

    def _covering(self, path: str) -> list[str]:
        out = []
        for d in self.limits:
            if d == "/" or path == d or path.startswith(d + "/"):
                out.append(d)
        return out

    async def _du(self, path: str) -> int:
        total = 0
        try:
            fd = await self.children[0].opendir(Loc(path))
            entries = await self.children[0].readdirp(fd)
        except FopError:
            return 0
        for name, ia in entries:
            if ia is None:
                continue
            child = path.rstrip("/") + "/" + name
            if ia.ia_type is IAType.DIR:
                total += await self._du(child)
            else:
                total += ia.size
        return total

    async def _use(self, d: str) -> int:
        if d not in self._usage:
            self._usage[d] = await self._du(d if d != "/" else "/")
        return self._usage[d]

    async def _check(self, path: str, delta: int) -> None:
        """quota_check_limit analog: would +delta exceed any covering
        limit?"""
        if delta <= 0:
            return
        for d in self._covering(path):
            used = await self._use(d)
            if used + delta > self.limits[d]:
                raise FopError(errno.EDQUOT,
                               f"quota exceeded on {d} "
                               f"({used}+{delta} > {self.limits[d]})")

    def _account(self, path: str, delta: int) -> None:
        for d in self._covering(path):
            if d in self._usage:
                self._usage[d] = max(0, self._usage[d] + delta)

    # -- enforced fops -----------------------------------------------------

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        path = fd.path
        ia = await self.children[0].fstat(fd)
        growth = max(0, offset + len(data) - ia.size)
        await self._check(path, growth)
        ret = await self.children[0].writev(fd, data, offset, xdata)
        self._account(path, growth)
        return ret

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        try:
            ia, _ = await self.children[0].lookup(loc)
            delta = size - ia.size
        except FopError:
            delta = 0
        if delta > 0:
            await self._check(loc.path, delta)
        ret = await self.children[0].truncate(loc, size, xdata)
        self._account(loc.path, delta)
        return ret

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        try:
            ia, _ = await self.children[0].lookup(loc)
            size = ia.size
        except FopError:
            size = 0
        ret = await self.children[0].unlink(loc, xdata)
        self._account(loc.path, -size)
        return ret

    def dump_private(self) -> dict:
        return {"limits": dict(self.limits), "usage": dict(self._usage)}
