"""features/quota — directory usage limits with persistent marker
accounting.

Reference: xlators/features/quota (quota.c:635 quota_check_limit, the
enforcer) + xlators/features/marker (marker.c:469 contribution
accounting) + quotad (quotad-aggregator.c).  The reference splits the
job three ways: marker maintains per-directory size xattrs on each
brick, quota enforces limits, quotad aggregates across bricks.  Here
the brick-side layer does marker+enforcement in one place:

* usage per limited directory is tracked incrementally from
  write/truncate/unlink deltas and **persisted** in the directory's
  ``trusted.glusterfs.quota.size`` xattr (the marker analog) so it
  survives brick restarts without a re-crawl;
* backend bytes are scaled to logical bytes by ``usage-scale`` (volgen
  sets K for a disperse brick, where a fragment holds 1/K of the file;
  1 elsewhere) so limits mean the same thing on every volume type;
* ``quota_usage`` is the aggregator RPC surface quotad polls
  (quotad-aggregator.c lookup path).

Limits arrive via the ``limits`` option (JSON path->bytes), pushed by
glusterd through live reconfigure on ``volume quota limit-usage``.
"""

from __future__ import annotations

import errno
import json

from ..core.fops import FopError
from ..core.iatt import IAType
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("features.quota")

XA_LIMIT = "trusted.glusterfs.quota.limit-set"
XA_SIZE = "trusted.glusterfs.quota.size"


@register("features/quota")
class QuotaLayer(Layer):
    OPTIONS = (
        Option("limits", "str", default="{}",
               description="JSON {path: hard-limit-bytes} (logical)"),
        Option("usage-scale", "int", default=1,
               description="backend->logical byte factor (K on a "
                           "disperse brick; fragments hold 1/K)"),
        Option("default-soft-limit", "percent", default=80.0),
        Option("hard-timeout", "time", default="5"),
        Option("soft-timeout", "time", default="60",
               description="re-warn (and re-log) a directory sitting "
                           "over its soft limit at most this often "
                           "(features.soft-timeout)"),
        Option("alert-time", "time", default="3600",
               description="repeat the over-soft-limit alert event "
                           "after this long (features.alert-time)"),
        Option("deem-statfs", "bool", default="on",
               description="statfs on a quota'd volume reports the "
                           "quota limit as the size "
                           "(features.quota-deem-statfs, quota.c "
                           "quota_statfs)"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.limits: dict[str, int] = {}  # dir path -> logical bytes
        self._usage: dict[str, int] = {}  # dir path -> backend bytes
        self._soft_warned: set[str] = set()
        self._dirty: set[str] = set()  # dirs with unpersisted deltas
        self._persisted_at: dict[str, float] = {}
        # identities recently seen writing into an over-soft-limit
        # directory (identity -> last-seen monotonic) — the QoS plane's
        # backpressure feed (protocol/server polls qos_soft_clients and
        # SHAPES these writers instead of erroring them; the hard limit
        # still EDQUOTs in _check)
        self._soft_clients: dict = {}
        self._parse_limits(self.opts["limits"])

    def _parse_limits(self, text: str) -> None:
        try:
            raw = json.loads(text or "{}")
        except ValueError:
            log.warning(1, "%s: bad limits JSON ignored", self.name)
            return
        self.limits = {k.rstrip("/") or "/": int(v)
                       for k, v in raw.items()}

    async def init(self) -> None:
        await super().init()
        # seed usage from the persisted marker xattrs (no re-crawl)
        for d in list(self.limits):
            try:
                xa = await self.children[0].getxattr(Loc(d), XA_SIZE)
                val = (xa or {}).get(XA_SIZE)
                if val is not None:
                    self._usage[d] = int(val)
            except (FopError, ValueError, TypeError):
                pass

    def reconfigure(self, options: dict) -> None:
        super().reconfigure(options)
        old_usage = self._usage
        self._parse_limits(self.opts["limits"])
        # keep cached usage for directories that are still limited
        self._usage = {d: u for d, u in old_usage.items()
                       if d in self.limits}

    # -- admin API (quota CLI path / xattr interface) ----------------------

    def limit_set(self, path: str, limit: int) -> None:
        self.limits[path.rstrip("/") or "/"] = limit

    def limit_remove(self, path: str) -> None:
        d = path.rstrip("/") or "/"
        self.limits.pop(d, None)
        self._usage.pop(d, None)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        if XA_LIMIT in xattrs:
            self.limit_set(loc.path, int(xattrs[XA_LIMIT]))
            xattrs = {k: v for k, v in xattrs.items() if k != XA_LIMIT}
            if not xattrs:
                return {}
        return await self.children[0].setxattr(loc, xattrs, flags, xdata)

    async def quota_usage(self) -> dict:
        """Aggregator surface (quotad polls this): logical usage and
        limit per limited directory."""
        scale = self.opts["usage-scale"]
        out = {}
        for d, lim in self.limits.items():
            used = await self._use(d)
            out[d] = {"used": int(used * scale), "limit": lim}
        return out

    # -- accounting (the marker analog) ------------------------------------

    def _covering(self, path: str) -> list[str]:
        out = []
        for d in self.limits:
            if d == "/" or path == d or path.startswith(d + "/"):
                out.append(d)
        return out

    async def _du(self, path: str) -> int:
        total = 0
        try:
            fd = await self.children[0].opendir(Loc(path))
            entries = await self.children[0].readdirp(fd)
        except FopError:
            return 0
        for name, ia in entries:
            if ia is None:
                continue
            child = path.rstrip("/") + "/" + name
            if ia.ia_type is IAType.DIR:
                total += await self._du(child)
            else:
                total += ia.size
        return total

    # marker persistence is debounced: the xattr may trail the live
    # counter by up to _PERSIST_EVERY seconds (a crash loses only that
    # window's deltas — the reference marker journals for the same
    # reason); fini flushes the remainder
    _PERSIST_EVERY = 1.0

    async def _persist(self, d: str, force: bool = False) -> None:
        import time as _time

        now = _time.monotonic()
        if not force and now - self._persisted_at.get(d, 0.0) < \
                self._PERSIST_EVERY:
            self._dirty.add(d)
            return
        try:
            await self.children[0].setxattr(Loc(d),
                                            {XA_SIZE: self._usage[d]})
            self._persisted_at[d] = now
            self._dirty.discard(d)
        except FopError:
            pass  # directory may not exist yet; next delta re-tries

    async def fini(self) -> None:
        for d in list(self._dirty):
            if d in self._usage:
                await self._persist(d, force=True)
        await super().fini()

    async def _use(self, d: str) -> int:
        if d not in self._usage:
            self._usage[d] = await self._du(d if d != "/" else "/")
            await self._persist(d, force=True)
        return self._usage[d]

    async def _check(self, path: str, delta: int) -> None:
        """quota_check_limit analog on logical bytes; logs a one-shot
        warning past the soft limit."""
        if delta <= 0:
            return
        scale = self.opts["usage-scale"]
        for d in self._covering(path):
            used = (await self._use(d)) * scale
            lim = self.limits[d]
            if used + delta * scale > lim:
                raise FopError(errno.EDQUOT,
                               f"quota exceeded on {d} "
                               f"({int(used)}+{int(delta * scale)} > "
                               f"{lim})")
            soft = lim * self.opts["default-soft-limit"] / 100.0
            if used + delta * scale > soft:
                import time as _time

                now = _time.monotonic()
                # QoS backpressure feed: remember WHO is pushing this
                # directory over its soft limit (frame->root->client)
                from ..rpc import wire as _wire

                ident = _wire.CURRENT_CLIENT.get()
                if ident is not None:
                    self._soft_clients[ident] = now
                warned = getattr(self, "_soft_warned_at", None)
                if warned is None:
                    warned = self._soft_warned_at = {}
                last = warned.get(d)
                # features.soft-timeout: repeat the warning on a
                # cadence instead of once-ever; features.alert-time
                # paces the cluster event
                if last is None or \
                        now - last >= self.opts["soft-timeout"]:
                    warned[d] = now
                    log.warning(2, "%s: %s over soft limit (%d/%d)",
                                self.name, d, int(used), lim)
                alerts = getattr(self, "_alerted_at", None)
                if alerts is None:
                    alerts = self._alerted_at = {}
                if alerts.get(d) is None or \
                        now - alerts[d] >= self.opts["alert-time"]:
                    alerts[d] = now
                    from ..core.events import gf_event

                    gf_event("QUOTA_SOFT_LIMIT", path=d,
                             used=int(used), limit=int(lim))

    # soft-pressure attribution expires after this quiet interval: a
    # writer that backed off (or whose directory was cleaned up) stops
    # being shaped without any explicit reset
    _SOFT_TTL = 3.0

    def qos_soft_clients(self):
        """Identities currently driving some directory over its soft
        limit — polled by protocol/server's QoS engine (features/qos),
        which shapes their writes via admission delay instead of
        erroring them."""
        import time as _time

        now = _time.monotonic()
        self._soft_clients = {i: t for i, t in self._soft_clients.items()
                              if now - t < self._SOFT_TTL}
        return set(self._soft_clients)

    async def _account(self, path: str, delta: int) -> None:
        for d in self._covering(path):
            if d in self._usage:
                self._usage[d] = max(0, self._usage[d] + delta)
                await self._persist(d)

    async def statfs(self, loc: Loc, xdata: dict | None = None):
        """features.quota-deem-statfs (quota_statfs): when the volume
        root carries a limit, df reports the QUOTA as the filesystem
        size — the operator promised the tenant that much, not the
        whole backing disk."""
        out = await self.children[0].statfs(loc, xdata)
        if not self.opts["deem-statfs"]:
            return out
        lim = self.limits.get("/")
        if not lim:
            return out
        scale = self.opts["usage-scale"]
        used = (await self._use("/")) * scale
        bsize = max(1, out.get("bsize", 4096))
        out = dict(out)
        out["blocks"] = lim // bsize
        out["bfree"] = out["bavail"] = max(0, (lim - used)) // bsize
        return out

    # -- enforced fops -----------------------------------------------------

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        path = fd.path
        ia = await self.children[0].fstat(fd)
        growth = max(0, offset + len(data) - ia.size)
        await self._check(path, growth)
        ret = await self.children[0].writev(fd, data, offset, xdata)
        await self._account(path, growth)
        return ret

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        try:
            ia, _ = await self.children[0].lookup(loc)
            delta = size - ia.size
        except FopError:
            delta = 0
        if delta > 0:
            await self._check(loc.path, delta)
        ret = await self.children[0].truncate(loc, size, xdata)
        await self._account(loc.path, delta)
        return ret

    async def fallocate(self, fd: FdObj, mode: int, offset: int,
                        length: int, xdata: dict | None = None):
        ia = await self.children[0].fstat(fd)
        growth = max(0, offset + length - ia.size)
        await self._check(fd.path, growth)
        ret = await self.children[0].fallocate(fd, mode, offset, length,
                                               xdata)
        await self._account(fd.path, growth)
        return ret

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        try:
            ia, _ = await self.children[0].lookup(loc)
            size = ia.size
        except FopError:
            size = 0
        ret = await self.children[0].unlink(loc, xdata)
        await self._account(loc.path, -size)
        return ret

    def dump_private(self) -> dict:
        scale = self.opts["usage-scale"]
        return {"limits": dict(self.limits),
                "usage": {d: int(u * scale)
                          for d, u in self._usage.items()}}
