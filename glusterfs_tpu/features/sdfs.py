"""features/sdfs — serialize directory fops ("dentry fop serializer").

Reference: xlators/features/sdfs (sdfs.c): entry fops racing on one
directory (create/unlink/rename/mkdir...) are serialized with entrylks
on the parent, closing lookup/create races the individual xlators
would otherwise have to handle.  Here: a per-parent-directory asyncio
lock (this layer instance is the serialization domain, like the
entrylk domain in the reference); rename locks both parents in sorted
order to stay deadlock-free."""

from __future__ import annotations

import asyncio

from ..core.layer import Layer, Loc, register


def _parent(path: str) -> str:
    return path.rsplit("/", 1)[0] or "/"


class _DirLock:
    __slots__ = ("lock", "refs")

    def __init__(self):
        self.lock = asyncio.Lock()
        self.refs = 0


@register("features/sdfs")
class SdfsLayer(Layer):
    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._locks: dict[str, _DirLock] = {}
        self.serialized = 0

    def _acquire_entry(self, d: str) -> "_DirLock":
        e = self._locks.get(d)
        if e is None:
            e = self._locks[d] = _DirLock()
        e.refs += 1
        return e

    def _release_entry(self, d: str) -> None:
        e = self._locks.get(d)
        if e is None:
            return
        e.refs -= 1
        # refcounted eviction: only drop an entry no task references
        # (a bare .locked() check would race a waiter holding the old
        # object while a newcomer mints a fresh one)
        if e.refs <= 0:
            del self._locks[d]

    async def _serialized(self, dirs: list[str], op: str, args, kwargs):
        self.serialized += 1
        ordered = sorted(set(dirs))
        entries = [self._acquire_entry(d) for d in ordered]
        try:
            async with _MultiLock([e.lock for e in entries]):
                return await getattr(self.children[0], op)(*args,
                                                           **kwargs)
        finally:
            for d in ordered:
                self._release_entry(d)

    def dump_private(self) -> dict:
        return {"serialized": self.serialized,
                "dirs_tracked": len(self._locks)}


class _MultiLock:
    def __init__(self, locks):
        self.locks = locks

    async def __aenter__(self):
        taken = []
        try:
            for lk in self.locks:
                await lk.acquire()
                taken.append(lk)
        except BaseException:
            # cancellation mid-acquire must not leave earlier locks
            # held forever (every fop under that dir would hang)
            for lk in reversed(taken):
                lk.release()
            raise

    async def __aexit__(self, *exc):
        for lk in reversed(self.locks):
            lk.release()
        return False


def _entry_serialized(op_name: str, nloc: int):
    async def impl(self, *args, **kwargs):
        dirs = [_parent(a.path) for a in args[:nloc]
                if isinstance(a, Loc) and a.path]
        return await self._serialized(dirs or ["/"], op_name, args,
                                      kwargs)
    impl.__name__ = op_name
    return impl


for _op, _n in (("create", 1), ("mknod", 1), ("mkdir", 1),
                ("unlink", 1), ("rmdir", 1), ("symlink", 2),
                ("link", 2), ("rename", 2)):
    setattr(SdfsLayer, _op, _entry_serialized(_op, _n))
