"""features/trash — keep deleted/truncated files under /.trashcan.

Reference: xlators/features/trash (2.8k LoC): unlinks become renames
into a timestamped path inside the trash directory; an internal-op
escape hatch avoids recursion."""

from __future__ import annotations

import errno
import time

from ..core.fops import FopError
from ..core.layer import Layer, Loc, register
from ..core.options import Option

TRASH_DIR = ".trashcan"
# xdata flag internal engines (heal, rebalance, gsyncd) set on their
# own unlinks: those bypass the trash hold (trash.c internal_op)
INTERNAL_OP = "glusterfs_tpu.internal-op"


@register("features/trash")
class TrashLayer(Layer):
    OPTIONS = (
        Option("trash", "bool", default="on"),
        Option("trash-max-filesize", "size", default="5MB"),
        Option("trash-dir", "str", default=TRASH_DIR,
               description="name of the hold directory "
                           "(features.trash-dir)"),
        Option("eliminate-path", "str", default="",
               description="comma-separated path patterns deleted "
                           "directly, never trashed "
                           "(features.trash-eliminate-path)"),
        Option("internal-op", "bool", default="off",
               description="trash INTERNAL unlinks too (heal/"
                           "rebalance cleanup; features.trash-"
                           "internal-op) — default skips them like "
                           "the reference"),
    )

    def _dir(self) -> str:
        return str(self.opts["trash-dir"] or TRASH_DIR).strip("/")

    def _eliminated(self, path: str) -> bool:
        import fnmatch

        spec = str(self.opts["eliminate-path"])
        return any(fnmatch.fnmatch(path, p.strip())
                   for p in spec.split(",") if p.strip())

    async def init(self):
        await super().init()
        try:
            await self.children[0].mkdir(Loc("/" + self._dir()), 0o700)
        except FopError as e:
            if e.err != errno.EEXIST:
                raise

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        tdir = self._dir()
        internal = bool((xdata or {}).get(INTERNAL_OP))
        if not self.opts["trash"] or loc.path.startswith("/" + tdir) \
                or self._eliminated(loc.path) \
                or (internal and not self.opts["internal-op"]):
            return await self.children[0].unlink(loc, xdata)
        try:
            ia, _ = await self.children[0].lookup(loc)
            if ia.size > self.opts["trash-max-filesize"]:
                return await self.children[0].unlink(loc, xdata)
        except FopError:
            return await self.children[0].unlink(loc, xdata)
        stamp = time.strftime("%Y-%m-%d-%H%M%S")
        dest = f"/{tdir}/{loc.path.strip('/').replace('/', '_')}" \
               f"_{stamp}"
        await self.children[0].rename(loc, Loc(dest))
        return {}

    def dump_private(self) -> dict:
        return {"trash_dir": "/" + self._dir()}
