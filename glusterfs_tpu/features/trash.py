"""features/trash — keep deleted/truncated files under /.trashcan.

Reference: xlators/features/trash (2.8k LoC): unlinks become renames
into a timestamped path inside the trash directory; an internal-op
escape hatch avoids recursion."""

from __future__ import annotations

import errno
import time

from ..core.fops import FopError
from ..core.layer import Layer, Loc, register
from ..core.options import Option

TRASH_DIR = ".trashcan"


@register("features/trash")
class TrashLayer(Layer):
    OPTIONS = (
        Option("trash", "bool", default="on"),
        Option("trash-max-filesize", "size", default="5MB"),
    )

    async def init(self):
        await super().init()
        try:
            await self.children[0].mkdir(Loc("/" + TRASH_DIR), 0o700)
        except FopError as e:
            if e.err != errno.EEXIST:
                raise

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        if not self.opts["trash"] or loc.path.startswith("/" + TRASH_DIR):
            return await self.children[0].unlink(loc, xdata)
        try:
            ia, _ = await self.children[0].lookup(loc)
            if ia.size > self.opts["trash-max-filesize"]:
                return await self.children[0].unlink(loc, xdata)
        except FopError:
            return await self.children[0].unlink(loc, xdata)
        stamp = time.strftime("%Y-%m-%d-%H%M%S")
        dest = f"/{TRASH_DIR}/{loc.path.strip('/').replace('/', '_')}" \
               f"_{stamp}"
        await self.children[0].rename(loc, Loc(dest))
        return {}

    def dump_private(self) -> dict:
        return {"trash_dir": "/" + TRASH_DIR}
