"""features/index — the persisted pending-heal index (brick-side).

Reference: xlators/features/index/src/index.c (index_add :656,
index_del :686, xattrop_index_action :1020, option xattrop64-watchlist).
There, every xattrop whose result leaves a pending/dirty marker nonzero
links the file's GFID under ``.glusterfs/indices/xattrop/`` and removes
the link once the markers return to zero; the self-heal daemon crawls
that directory instead of the whole volume, which is what makes heal
O(pending) rather than O(files).

Same contract here, tpu-build mechanisms:

* watches the cluster layers' accounting keys (``trusted.ec.dirty``,
  ``trusted.afr.dirty`` — the watchlist option) on xattrop/fxattrop and
  setxattr/fsetxattr results;
* nonzero marker  -> touch ``<index-base>/xattrop/<gfid-hex>``;
  all markers zero -> unlink it;
* the index is listed through a virtual xattr
  (``glusterfs_tpu.index.xattrop`` -> newline-joined gfid hexes) — the
  reference exposes the same data as a virtual gfid directory
  (index.c index_readdir); a virtual setxattr
  (``glusterfs_tpu.index.prune`` = hex) drops a stale entry, which the
  shd uses when an indexed gfid no longer resolves.

``index-base`` defaults to ``<posix-root>/.glusterfs_tpu/indices`` found
by walking down to the storage/posix descendant.
"""

from __future__ import annotations

import errno
import os

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("index")

XA_INDEX_LIST = "glusterfs_tpu.index.xattrop"
XA_INDEX_COUNT = "glusterfs_tpu.index.count"
XA_INDEX_PRUNE = "glusterfs_tpu.index.prune"

DEFAULT_WATCH = "trusted.ec.dirty,trusted.afr.dirty"


def _nonzero(val: bytes) -> bool:
    return any(val)


@register("features/index")
class IndexLayer(Layer):
    OPTIONS = (
        Option("index-base", "path", default="",
               description="index store directory (default: "
                           "<posix-root>/.glusterfs_tpu/indices)"),
        Option("watchlist", "str", default=DEFAULT_WATCH,
               description="comma-separated pending xattr keys "
                           "(reference xattrop64-watchlist)"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.watch = tuple(k.strip() for k in
                           str(self.opts["watchlist"]).split(",") if k.strip())
        self._dir: str | None = None

    async def init(self):
        base = self.opts.get("index-base")
        if not base:
            posix = self._find_posix(self)
            if posix is None:
                raise ValueError(f"{self.name}: no index-base and no "
                                 f"storage/posix descendant")
            base = os.path.join(posix.root, ".glusterfs_tpu", "indices")
        self._dir = os.path.join(os.path.abspath(base), "xattrop")
        os.makedirs(self._dir, exist_ok=True)
        await super().init()

    @staticmethod
    def _find_posix(layer: Layer):
        stack = list(layer.children)
        while stack:
            l = stack.pop()
            if l.type_name == "storage/posix":
                return l
            stack.extend(l.children)
        return None

    # -- the index itself ----------------------------------------------------

    def _entry(self, gfid: bytes) -> str:
        return os.path.join(self._dir, gfid.hex())

    def _add(self, gfid: bytes) -> None:
        try:
            with open(self._entry(gfid), "x"):
                pass
        except FileExistsError:
            pass
        except OSError as e:
            log.error(1, "%s: index add %s failed: %s",
                      self.name, gfid.hex(), e)

    def _del(self, gfid: bytes) -> None:
        try:
            os.unlink(self._entry(gfid))
        except FileNotFoundError:
            pass

    def list_entries(self) -> list[str]:
        try:
            return sorted(os.listdir(self._dir))
        except OSError:
            return []

    # -- tracking ------------------------------------------------------------

    async def _gfid_for(self, loc: Loc | None, fd: FdObj | None) -> bytes | None:
        if fd is not None and fd.gfid:
            return fd.gfid
        if loc is not None:
            if loc.gfid:
                return loc.gfid
            try:
                ia, _ = await self.children[0].lookup(loc)
                return ia.gfid
            except FopError:
                return None
        return None

    async def _track(self, loc: Loc | None, fd: FdObj | None,
                     values: dict) -> None:
        """Re-evaluate the index entry after watched keys changed to
        ``values`` (absolute resulting values, xattrop result or setxattr
        payload)."""
        touched = {k: v for k, v in values.items() if k in self.watch}
        if not touched:
            return
        gfid = await self._gfid_for(loc, fd)
        if gfid is None:
            return
        if any(_nonzero(v if isinstance(v, bytes) else bytes(v))
               for v in touched.values()):
            self._add(gfid)
            return
        # the touched markers are zero; the entry may only be dropped when
        # EVERY watched marker is zero (another cluster layer may still
        # have a pending mark on the same object)
        try:
            allx = await self.children[0].getxattr(
                Loc(loc.path if loc else "", gfid=gfid), None)
        except FopError:
            allx = {}
        if any(_nonzero(allx.get(k, b"")) for k in self.watch):
            return
        self._del(gfid)

    # -- intercepted fops ------------------------------------------------------

    async def xattrop(self, loc: Loc, op: str, xattrs: dict,
                      xdata: dict | None = None):
        out = await self.children[0].xattrop(loc, op, xattrs, xdata)
        await self._track(loc, None, out)
        return out

    async def fxattrop(self, fd: FdObj, op: str, xattrs: dict,
                       xdata: dict | None = None):
        out = await self.children[0].fxattrop(fd, op, xattrs, xdata)
        await self._track(None, fd, out)
        return out

    async def writev(self, fd: FdObj, data, offset: int = 0,
                     xdata: dict | None = None):
        """Compound pre-op: a ``pre-xattrop`` payload in xdata applies
        (and index-tracks) the dirty marker in the SAME brick round as
        the data write — the client saves a full fan-out wave, the
        crash-ordering guarantee is unchanged (marker lands before the
        data, both inside this one brick op)."""
        pre = (xdata or {}).get("pre-xattrop")
        if pre:
            xdata = {k: v for k, v in xdata.items() if k != "pre-xattrop"}
            await self.fxattrop(fd, "add64", dict(pre), None)
        return await self.children[0].writev(fd, data, offset, xdata)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        if XA_INDEX_PRUNE in xattrs:
            val = xattrs[XA_INDEX_PRUNE]
            hexgfid = (val.decode() if isinstance(val, bytes) else str(val))
            self._del(bytes.fromhex(hexgfid))
            return {}
        out = await self.children[0].setxattr(loc, xattrs, flags, xdata)
        await self._track(loc, None, xattrs)
        return out

    async def fsetxattr(self, fd: FdObj, xattrs: dict, flags: int = 0,
                        xdata: dict | None = None):
        out = await self.children[0].fsetxattr(fd, xattrs, flags, xdata)
        await self._track(None, fd, xattrs)
        return out

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        if name == XA_INDEX_LIST:
            return {name: "\n".join(self.list_entries()).encode()}
        if name == XA_INDEX_COUNT:
            return {name: str(len(self.list_entries())).encode()}
        return await self.children[0].getxattr(loc, name, xdata)

    def dump_private(self) -> dict:
        return {"dir": self._dir, "pending": len(self.list_entries()),
                "watch": list(self.watch)}
