"""features/gfid-access — the virtual ``/.gfid/<uuid>`` access path.

Reference: xlators/features/gfid-access (gfid-access.c): geo-rep's
secondary addresses objects by gfid without knowing their path;
``/.gfid/<hex-or-dashed-uuid>`` resolves straight to the inode.  Here:
paths under /.gfid are rewritten to gfid-addressed Locs (the posix
store resolves those natively via its handle farm)."""

from __future__ import annotations

import errno
import uuid as uuid_mod

from ..core.fops import Fop, FopError
from ..core.layer import Layer, Loc, register

GFID_DIR = "/.gfid"


def _parse(path: str) -> bytes | None:
    """/.gfid/<uuid>[/...] -> gfid bytes (sub-paths unsupported, like
    the reference's aux-gfid-mount)."""
    rest = path[len(GFID_DIR):].lstrip("/")
    if not rest or "/" in rest:
        return None
    try:
        return uuid_mod.UUID(rest).bytes
    except ValueError:
        try:
            raw = bytes.fromhex(rest)
            return raw if len(raw) == 16 else None
        except ValueError:
            return None


@register("features/gfid-access")
class GfidAccessLayer(Layer):
    @staticmethod
    def _rewrite(loc: Loc) -> Loc:
        if not loc.path or not loc.path.startswith(GFID_DIR):
            return loc
        if loc.path == GFID_DIR:
            raise FopError(errno.EPERM, ".gfid is virtual")
        gfid = _parse(loc.path)
        if gfid is None:
            raise FopError(errno.EINVAL,
                           f"bad gfid path {loc.path!r}")
        return Loc("", gfid=gfid)

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        if loc.path == GFID_DIR:
            # the virtual dir itself resolves (path walkers visit it on
            # the way to /.gfid/<uuid>), ga_virtual_lookup style
            from ..core.virtfs import virtual_dir_iatt, virtual_gfid

            return virtual_dir_iatt(virtual_gfid("gfid-access",
                                                 GFID_DIR)), {}
        return await self.children[0].lookup(self._rewrite(loc), xdata)


def _rewriting(op_name: str):
    async def impl(self, *args, **kwargs):
        args = tuple(self._rewrite(a) if isinstance(a, Loc) else a
                     for a in args)
        return await getattr(self.children[0], op_name)(*args, **kwargs)
    impl.__name__ = op_name
    return impl


for _f in Fop:
    # keep custom lookup; COMPOUND stays on Layer.compound so chains
    # DECOMPOSE here and each link's /.gfid/<uuid> Loc is rewritten —
    # the _rewriting wrapper would forward a chain intact with raw
    # virtual paths inside its links
    if _f.value not in GfidAccessLayer.__dict__ and \
            _f is not Fop.COMPOUND:
        setattr(GfidAccessLayer, _f.value, _rewriting(_f.value))
