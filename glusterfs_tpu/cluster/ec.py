"""cluster/disperse — Reed-Solomon erasure coding across N brick subvolumes.

The reference's cluster/ec xlator (reference xlators/cluster/ec/src/) in
TPU-build form.  Capabilities kept, mechanisms re-designed:

* **Geometry** (ec-types.h:627-680): N = K + R children; every file is
  striped in ``stripe = K*512`` byte stripes; brick i stores fragment i —
  512 bytes per stripe — at ``offset/K``.  Non-systematic code: every
  fragment (including the first K) is matrix output (ec-method.c:284-287).
* **Write path** (ec-inode-write.c:2141-2231): partial-stripe head/tail
  read-modify-write, encode via the unified TPU codec (ops/codec.py — the
  ``disperse.cpu-extensions`` analog), dispatch-all fragment writes,
  op_ret rescaled to user bytes.
* **Read path** (ec-inode-read.c:1148-1230): dispatch-min — read any K
  fragments per read-policy, decode, trim head/tail; degraded reads pick
  surviving bricks by the same path.
* **Transactions** (ec-common.c:2377, doc afr-style): per-write pre-op
  ``dirty+1`` / post-op ``version+1, dirty-1`` xattrop on each brick;
  version divergence marks heal candidates; quorum below K fails the fop
  (ec.c:308-316 down_count semantics).
* **Heal** (ec-heal.c:1658,2048): compare versions, decode from the good
  K, re-encode onto the bad bricks, reset their version/size/dirty.

Xattr schema on each brick (trusted.ec.* like the reference):
``trusted.ec.version`` = 2 big-endian u64 (data, metadata);
``trusted.ec.size`` = u64 true file size; ``trusted.ec.dirty`` = 2 u64.
"""

from __future__ import annotations

import asyncio
import errno
import struct
from collections import Counter

import numpy as np

from ..core.fops import FopError
from ..core.iatt import IAType, Iatt, gfid_new
from ..core.layer import Event, FdObj, Layer, Loc, register
from ..core import metrics as _metrics

#: live disperse layers, scraped (not owned) by the unified registry —
#: weak so a retired graph's layers age out with the GC
_LIVE_EC_LAYERS = _metrics.REGISTRY.register_objects(
    "gftpu_ec_read_fanout_total", "counter",
    "EC readv fan-outs by mode (fast = zero-staging systematic "
    "reassembly, staged = decode through the frags array)",
    lambda l: [({"layer": l.name, "mode": m}, v)
               for m, v in l.read_fanout.items()])
_metrics.REGISTRY.register_objects(
    "gftpu_ec_readv_coalesced_total", "counter",
    "adjacent readv chain links merged into single ranged fragment "
    "fan-outs (chains = merged dispatches, links = member readvs "
    "absorbed)",
    lambda l: [({"layer": l.name, "what": m}, v)
               for m, v in l.read_coalesced.items()],
    live=_LIVE_EC_LAYERS)
# parity-delta write plane (ISSUE 10): which path each unaligned write
# took, and what the delta path saved over the full read-modify-write
_metrics.REGISTRY.register_objects(
    "gftpu_ec_delta_writes_total", "counter",
    "sub-stripe writes served by the parity-delta path (touched data "
    "slices + brick-side parity xorv; no k-fragment decode)",
    lambda l: [({"layer": l.name, "origin": o}, v)
               for o, v in l.delta_origin.items()],
    live=_LIVE_EC_LAYERS)
_metrics.REGISTRY.register_objects(
    "gftpu_ec_rmw_writes_total", "counter",
    "unaligned writes that paid the full read-modify-write (degraded, "
    "non-systematic, EOF-crossing, delta-writes off, or a peer "
    "without xorv)",
    lambda l: [({"layer": l.name}, l.write_path["rmw"])],
    live=_LIVE_EC_LAYERS)
_metrics.REGISTRY.register_objects(
    "gftpu_ec_delta_bytes_saved_total", "counter",
    "fragment bytes the delta path did NOT move versus the full RMW "
    "it replaced (dir=read: decode-source bytes not read; dir=write: "
    "fragment bytes not rewritten)",
    lambda l: [({"layer": l.name, "dir": d}, v)
               for d, v in l.delta_saved.items()],
    live=_LIVE_EC_LAYERS)
from ..core.options import Option
from ..core import gflog
from ..core import tracing as _tracing
from ..ops import codec as codec_mod
from ..rpc import wire

import time as _time

log = gflog.get_logger("ec")


class _DeltaFallback(Exception):
    """Internal: the parity-delta path bailed before committing
    anything it cannot undo (live-downgraded peer, failed internal
    read) — the caller redoes the write through the full-RMW path,
    which rewrites every fragment of the region and converges any
    partially-applied wave."""

XA_VERSION = "trusted.ec.version"
XA_SIZE = "trusted.ec.size"
XA_DIRTY = "trusted.ec.dirty"

CHUNK = 512


def _u64x2(data: bytes | None) -> tuple[int, int]:
    if not data:
        return (0, 0)
    return struct.unpack(">QQ", data.ljust(16, b"\0")[:16])


def _pack_u64x2(a: int, b: int) -> bytes:
    return struct.pack(">QQ", a, b)


class ECFdCtx:
    """Per-EC-fd state: one child fd per brick (index -> FdObj|None)."""

    __slots__ = ("child_fds", "flags")

    def __init__(self, child_fds: dict[int, FdObj], flags: int):
        self.child_fds = child_fds
        self.flags = flags


class _EagerState:
    """One held eager transaction window (the ec_lock_t analog,
    ec-common.c:2176 eager-lock reuse + delayed post-op): the cluster
    inodelk stays held across consecutive fops on the same inode, the
    (candidates, size) metadata is cached under it, the pre-op dirty
    mark is set once, and ONE combined version+size+dirty xattrop
    commits at window close."""

    __slots__ = ("owner", "locked", "pre", "good", "candidates", "size",
                 "delta", "timer", "opened", "inflight", "idle",
                 "pre_landed", "ranges", "rseq")

    def __init__(self, owner: bytes, locked: list[int],
                 candidates: list[int], size: int, good: set[int],
                 opened: float):
        self.owner = owner
        self.locked = locked          # bricks holding our inodelk
        self.pre: set[int] = set()    # bricks that got the dirty+1 pre-op
        self.good = good              # bricks that took EVERY write so far
        self.candidates = candidates  # consistent read rows (cached meta)
        self.size = size              # current true size (cached meta)
        self.delta = 0                # pending data-version increments
        self.timer = None             # deferred-release handle
        self.opened = opened          # loop time: bounds total hold
        # parallel-writes state (ec_is_range_conflict, ec-common.c:185):
        # non-conflicting write waves run outside the local gfid lock
        self.inflight = 0             # write-class waves mid-dispatch
        self.idle = asyncio.Event()   # set while inflight == 0
        self.idle.set()
        self.pre_landed = asyncio.Event()  # dirty+1 is ON the bricks
        self.ranges: dict[int, tuple[int, int, asyncio.Future]] = {}
        self.rseq = 0

    def conflict(self, a_off: int, a_end: int) -> "asyncio.Future | None":
        """Completion future of an overlapping in-flight write, if any."""
        for off, end, fut in self.ranges.values():
            if off < a_end and a_off < end:
                return fut
        return None

    def add_range(self, a_off: int, a_end: int) -> int:
        self.rseq += 1
        fut = asyncio.get_running_loop().create_future()
        self.ranges[self.rseq] = (a_off, a_end, fut)
        return self.rseq

    def del_range(self, token: int) -> None:
        """Lock-free on purpose: waiters may hold the gfid lock while
        they wait for us (quiesce), so removal must not need it."""
        ent = self.ranges.pop(token, None)
        if ent is not None and not ent[2].done():
            ent[2].set_result(None)


@register("cluster/disperse")
class DisperseLayer(Layer):
    OPTIONS = (
        Option("redundancy", "int", default=2, min=1, max=8),
        Option("cpu-extensions", "enum", default="auto",
               values=("auto", "ref", "native", "xla", "xla-xor",
                       "pallas-xor", "pallas-mxu", "mesh"),
               description="codec backend (reference disperse.cpu-extensions"
                           " {none,auto,x64,sse,avx} -> TPU ladder; mesh ="
                           " multi-chip sharded data plane)"),
        Option("read-policy", "enum", default="round-robin",
               values=("round-robin", "gfid-hash", "first-k")),
        Option("ec-read-mask", "str", default="",
               description="comma-separated child indices allowed to "
                           "serve reads (ec_assign_read_mask, "
                           "ec.c:717-775): keeps a slow or suspect "
                           "brick out of the read set.  Strict, like "
                           "the reference (fop->mask &= read_mask, "
                           "ec-inode-read.c:1375): a masked-out brick "
                           "never serves reads, even degraded.  Must "
                           "name at least K ids; invalid masks log and "
                           "clear"),
        Option("parallel-writes", "bool", default="on",
               description="writes touching disjoint stripe ranges of "
                           "one inode dispatch concurrently inside the "
                           "eager window instead of serializing "
                           "(disperse.parallel-writes, ec.c:284,868 + "
                           "ec_is_range_conflict ec-common.c:185)"),
        Option("quorum-count", "int", default=0, min=0,
               description="extra write quorum (0 = K)"),
        Option("delta-writes", "bool", default="on",
               description="parity-delta sub-stripe writes "
                           "(cluster.delta-writes, op-version 12): on a "
                           "HEALTHY systematic volume an unaligned "
                           "write inside the file reads back only the "
                           "bytes it overwrites from the touched data "
                           "fragments, forms Δ = old ⊕ new, and "
                           "dispatches the touched data slices as "
                           "writev plus parity(Δ) as brick-side xorv — "
                           "one wave, no k-fragment decode, no "
                           "n-fragment rewrite (the classic RAID "
                           "parity-logging result; linearity: "
                           "frag(old ⊕ Δ) = frag(old) ⊕ frag(Δ)).  "
                           "Degraded / non-systematic / EOF-crossing "
                           "writes (and peers without xorv) keep the "
                           "full read-modify-write path byte-"
                           "identically"),
        Option("systematic", "bool", default="off",
               description="systematic generator matrix "
                           "(gf256.systematic_matrix): data fragments "
                           "are raw stripe chunks, so healthy reads "
                           "skip decode entirely, encode ships only "
                           "parity to the device, and degraded reads "
                           "reconstruct only missing rows — the "
                           "tpu-first layout when the accelerator sits "
                           "behind a bandwidth-bound link.  The "
                           "reference's code is non-systematic "
                           "(ec-method.c:393-433; every read decodes). "
                           "Fragment formats are incompatible: fixed "
                           "at volume create, immutable live"),
        Option("self-heal-window-size", "size", default="1M"),
        Option("stripe-cache", "bool", default="on",
               description="coalesce concurrent fop codec work into one "
                           "device batch per tick (ec.c:286 analog)"),
        Option("stripe-cache-window", "int", default=0, min=0,
               description="batching window in microseconds; 0 = "
                           "same-tick coalescing (flush on the next "
                           "loop pass — concurrent fops still batch, "
                           "a lone sequential writer never waits)"),
        Option("stripe-cache-min-batch", "size", default="256KB",
               description="batches below this run on the CPU ladder"),
        Option("mesh-codec", "bool", default="off",
               description="shard coalesced stripe batches over the "
                           "(dp, frag) device mesh: flushes at/above "
                           "stripe-cache-min-batch land in ONE pjit'd "
                           "NamedSharding launch when >1 jax device is "
                           "visible (parallel/mesh_codec — the ICI "
                           "analog of ec_dispatch_all's socket "
                           "fan-out).  On 1 device, below min-batch, "
                           "or on a systematic volume the existing "
                           "ladder is untouched; rides the "
                           "stripe-cache batching window"),
        Option("eager-lock", "bool", default="on",
               description="hold the txn inodelk across consecutive fops "
                           "on one inode with a delayed combined post-op "
                           "(disperse.eager-lock, ec-common.c:2176)"),
        Option("other-eager-lock", "bool", default="on",
               description="non-write fops (reads) share the eager "
                           "window too (disperse.other-eager-lock): "
                           "consecutive reads on one inode pay one lock "
                           "wave total.  The window's inodelk is "
                           "exclusive, so cross-CLIENT concurrent "
                           "readers of one file serialize on lock "
                           "handoffs — turn this off for that workload "
                           "(reads then take shared rd locks per fop), "
                           "same tradeoff the reference documents"),
        Option("eager-lock-timeout", "time", default="0.2",
               description="idle window before the eager lock releases "
                           "(reference post-op-delay semantics)"),
        Option("other-eager-lock-timeout", "time", default="0.2",
               description="separate release timeout for CLEAN "
                           "(read-only) windows "
                           "(disperse.other-eager-lock-timeout)"),
        Option("eager-lock-max-hold", "time", default="1",
               description="hard cap on one window's total hold time — "
                           "bounds how long a continuous writer can "
                           "starve other clients of the inodelk (the "
                           "reference yields on contention upcall; "
                           "brick locks queue FIFO, so the waiting "
                           "client gets the lock at the cap)"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.n = len(self.children)
        self.r = self.opts["redundancy"]
        self.k = self.n - self.r
        if self.k < 1 or self.r < 1:
            raise ValueError(
                f"{self.name}: need K>=1, R>=1 (n={self.n}, r={self.r})")
        if self.k > 16:
            raise ValueError(f"{self.name}: K={self.k} exceeds max 16")
        from ..ops.batch import BatchingCodec

        self.codec = BatchingCodec(
            self.k, self.r, self.opts["cpu-extensions"],
            window=self.opts["stripe-cache-window"] / 1e6,
            min_batch=self.opts["stripe-cache-min-batch"],
            systematic=self.opts["systematic"],
            mesh=self.opts["mesh-codec"], name=self.name)
        self._batching = self.opts["stripe-cache"]
        # default origin label for this layer's codec traffic on the
        # batch/mesh metrics families: "serve" for a client mount; the
        # rebalance daemon tags its PRIVATE graph "rebalance" so mesh
        # launches and counters attribute migration I/O (the shd heal
        # precedent — explicit origin="heal" call sites still win)
        self.traffic_origin = "serve"
        self.stripe = self.k * CHUNK
        self.up = [True] * self.n  # xl_up bitmask (ec.c:571 notify)
        self._locks: dict[bytes, asyncio.Lock] = {}
        self._rr = 0  # read-policy round-robin cursor
        from ..core.iatt import gfid_new as _g

        self._lk_owner = _g()  # this client's lk-owner identity
        self._locks_supported: bool | None = None  # lazily probed
        self._eager: dict[bytes, _EagerState] = {}  # gfid -> held window
        self._bg: set[asyncio.Task] = set()  # strong refs to drain tasks
        self._read_mask = self._parse_read_mask()
        # read fan-out accounting (ISSUE 3): "fast" = healthy systematic
        # reassembly straight from fragment buffers (no staging copy),
        # "staged" = the decode path through the frags array
        self.read_fanout = {"fast": 0, "staged": 0}
        # fragment-readv coalescing (ROADMAP item 7): adjacent readv
        # links of one compound chain merged into ONE ranged brick
        # read per fan-out
        self.read_coalesced = {"chains": 0, "links": 0}
        # parity-delta write plane (ISSUE 10): path taken per unaligned
        # write + fragment bytes the delta path saved over full RMW
        self.write_path = {"delta": 0, "rmw": 0}
        # delta writes split by traffic_origin ("serve" vs "rebalance"
        # vs "heal"): write_path["delta"] stays the total; this dict
        # feeds the per-origin samples on the registry family so an
        # operator can see migration I/O riding the delta plane
        self.delta_origin = {"serve": 0}
        self.delta_saved = {"read": 0, "write": 0}
        # live-downgrade memory: a parity brick answering EOPNOTSUPP to
        # xorv parks the WHOLE layer on the RMW path (parity rows are
        # fixed brick indices — one refusing brick breaks every delta)
        self._xorv_ok = True
        # last announced "≥K children up" state (events.h
        # EVENT_EC_MIN_BRICKS_UP / _NOT_UP fire on the transition)
        self._min_up_ok = True
        _LIVE_EC_LAYERS.add(self)  # unified-registry scrape target

    def reconfigure(self, options: dict) -> None:
        """Live option apply (ec_reconfigure, ec.c:254): codec backend /
        batching options rebuild the codec; geometry (redundancy) is
        immutable on a live volume."""
        old = dict(self.opts)
        super().reconfigure(options)
        if self.opts["redundancy"] != self.r:
            log.warning(3, "%s: redundancy is immutable live (%d -> %d "
                        "ignored)", self.name, self.r,
                        self.opts["redundancy"])
            self.opts["redundancy"] = self.r
        if self.opts["systematic"] != old["systematic"]:
            # the fragment format on the bricks: flipping it live would
            # make every existing file decode to garbage
            log.warning(3, "%s: systematic is immutable live (ignored)",
                        self.name)
            self.opts["systematic"] = old["systematic"]
        codec_keys = ("cpu-extensions", "stripe-cache-window",
                      "stripe-cache-min-batch", "mesh-codec")
        if any(self.opts[k] != old[k] for k in codec_keys):
            from ..ops.batch import BatchingCodec

            self.codec.close()  # release the replaced codec's pool
            self.codec = BatchingCodec(
                self.k, self.r, self.opts["cpu-extensions"],
                window=self.opts["stripe-cache-window"] / 1e6,
                min_batch=self.opts["stripe-cache-min-batch"],
                systematic=self.opts["systematic"],
                mesh=self.opts["mesh-codec"], name=self.name)
        self._batching = self.opts["stripe-cache"]
        self._read_mask = self._parse_read_mask()
        if self.opts["delta-writes"]:
            # re-arm the downgrade memory on ANY reconfigure that
            # leaves the key on: volume-set is the operator's "bricks
            # were upgraded, try again" signal, and a still-downgraded
            # peer re-parks at the client-side capability gate for the
            # cost of one local EOPNOTSUPP (no round trip)
            self._xorv_ok = True

    def _parse_read_mask(self) -> frozenset[int] | None:
        """ec_assign_read_mask (ec.c:717-775): parse + validate — every
        id a real child index, at least K ids total.  The reference
        fails the option set; our reconfigure path logs and clears."""
        raw = str(self.opts["ec-read-mask"] or "").strip()
        if not raw:
            return None
        try:
            ids = frozenset(int(p) for p in raw.split(",") if p.strip())
        except ValueError:
            log.warning(3, "%s: ec-read-mask %r has a non-integer id; "
                        "ignoring mask", self.name, raw)
            return None
        if any(i < 0 or i >= self.n for i in ids):
            log.warning(3, "%s: ec-read-mask %r id out of range [0-%d]; "
                        "ignoring mask", self.name, raw, self.n - 1)
            return None
        if len(ids) < self.k:
            log.warning(3, "%s: ec-read-mask %r names fewer than K=%d "
                        "ids; ignoring mask", self.name, raw, self.k)
            return None
        return ids

    # -- child state -------------------------------------------------------

    def notify(self, event: Event, source=None, data=None):
        if event is Event.UPCALL:
            if isinstance(data, dict) and \
                    data.get("event") == "inodelk-contention" and \
                    data.get("gfid") in self._eager:
                # another client (or a snapshot quiesce) wants our
                # inodelk: commit the delayed post-op and release NOW
                # instead of sitting out the post-op delay
                # (ec_upcall GF_UPCALL_INODELK_CONTENTION ->
                # ec_lock_release, ec-common.c:2576-2582)
                gfid = data["gfid"]
                t = asyncio.get_event_loop().create_task(
                    self._eager_drain(Loc("", gfid=gfid), gfid))
                self._bg.add(t)
                t.add_done_callback(self._bg.discard)
            # upcalls pass through untranslated (ec_notify forwards
            # GF_EVENT_UPCALL to parents as-is)
            for p in self.parents:
                p.notify(event, self, data)
            return
        if source in self.children:
            idx = self.children.index(source)
            if event is Event.CHILD_DOWN:
                self.up[idx] = False
                log.warning(1, "%s: child %s down (%d/%d up)", self.name,
                            source.name, sum(self.up), self.n)
            elif event is Event.CHILD_UP:
                self.up[idx] = True
            ok = sum(self.up) >= self.k
            if ok != self._min_up_ok:
                # read-quorum edge (ec_notify, ec.c:571): below K the
                # disperse set can neither read nor write
                self._min_up_ok = ok
                from ..core.events import gf_event

                gf_event("EC_MIN_BRICKS_UP" if ok
                         else "EC_MIN_BRICKS_NOT_UP",
                         subvol=self.name, up=sum(self.up), k=self.k,
                         children=self.n)
            if sum(self.up) >= self.k:
                for p in self.parents:
                    p.notify(Event.CHILD_UP if event is Event.CHILD_UP
                             else Event.SOME_DESCENDENT_DOWN, self, data)
            else:
                for p in self.parents:
                    p.notify(Event.CHILD_DOWN, self, data)
            return
        super().notify(event, source, data)

    def set_child_up(self, idx: int, up: bool) -> None:
        """Test/heal hook: mark a brick up/down."""
        self.up[idx] = up

    def _up_idx(self) -> list[int]:
        return [i for i, u in enumerate(self.up) if u]

    def _write_quorum(self) -> int:
        q = self.opts["quorum-count"]
        return max(self.k, q) if q else self.k

    def _lock(self, key: bytes) -> asyncio.Lock:
        lk = self._locks.get(key)
        if lk is None:
            lk = self._locks[key] = asyncio.Lock()
        return lk

    # -- cluster-wide transaction locks (ec-locks.c / ec_lock analog) ------

    async def _inodelk_wind(self, loc: Loc, ltype: str,
                            owner: bytes | None = None,
                            start: int = 0, end: int = -1,
                            collect: dict | None = None) -> list[int]:
        """Take an inodelk on every up child (brick-side features/locks);
        children without a locks layer (EOPNOTSUPP) are skipped.  Locks
        are wound in index order — all clients use the same order, so
        cross-client deadlock cannot occur (ec-locks.c ordering).
        ``start``/``end`` bound the byte range (end exclusive, -1 =
        EOF): writes lock the whole file, heal locks one window at a
        time (ec_heal_inodelk offset/size, ec-heal.c:251).
        ``collect``: lock-and-fetch — each grant returns the inode's
        xattrs (collect[i] = dict), folding the window's metadata
        fan-out into the lock wave."""
        if self._locks_supported is False:
            return []
        xd = {"lk-owner": owner or self._lk_owner}
        if collect is not None:
            xd["get-xattrs"] = True

        def absorb(i: int, ret) -> None:
            # only trust fetches that carry real counter state: a failed
            # fetch (None), a locks layer predating get-xattrs (None
            # grant return), or a brick whose counters are simply absent
            # must NOT be parsed as "clean version 0, size 0" — that
            # fabricated entry could win _pick_meta's vote and corrupt
            # the recorded size.  Missing entries force the caller back
            # to the classic metadata wave.
            if collect is not None and isinstance(ret, dict) \
                    and XA_VERSION in ret:
                collect[i] = ret

        # Fast path (ec-locks.c / afr_lock: try NON-BLOCKING on every
        # child in ONE parallel wave; conflicts fall back to the ordered
        # blocking walk).  The sequential walk alone costs up-count
        # round trips of pure latency per transaction.
        ups = self._up_idx()
        res = await self._dispatch(
            ups, "inodelk",
            lambda i: (("ec.transaction", loc, "lock-nb", ltype, start,
                        end, xd), {}))
        granted = [i for i, r in res.items()
                   if not isinstance(r, BaseException)]
        errs = {i: r for i, r in res.items() if isinstance(r, BaseException)}
        if all(isinstance(e, FopError) and e.err == errno.EOPNOTSUPP
               for e in errs.values()):
            for i in granted:
                absorb(i, res[i])
            if self._locks_supported is None:
                self._locks_supported = bool(granted)
            return sorted(granted)
        # somebody holds a conflicting lock (or a brick failed): release
        # what we took and walk children in index order with BLOCKING
        # locks — all clients use the same order, so cross-client
        # deadlock cannot occur (ec-locks.c ordering)
        await self._inodelk_unwind(loc, sorted(granted), owner, start, end)
        if collect is not None:
            collect.clear()
        locked: list[int] = []
        try:
            for i in ups:
                try:
                    ret = await self.children[i].inodelk(
                        "ec.transaction", loc, "lock", ltype, start, end,
                        xd)
                    locked.append(i)
                    absorb(i, ret)
                except FopError as e:
                    if e.err == errno.EOPNOTSUPP:
                        continue
                    raise
        except FopError:
            await self._inodelk_unwind(loc, locked, owner, start, end)
            raise
        if self._locks_supported is None:
            self._locks_supported = bool(locked)
        return locked

    async def _inodelk_unwind(self, loc: Loc, locked: list[int],
                              owner: bytes | None = None,
                              start: int = 0, end: int = -1) -> None:
        if not locked:
            return
        xd = {"lk-owner": owner or self._lk_owner}
        # one parallel wave; failures (restarted brick: lock already
        # reaped) are ignored per child
        await self._dispatch(
            list(locked), "inodelk",
            lambda i: (("ec.transaction", loc, "unlock", "wr", start, end,
                        xd), {}))

    class _Txn:
        """Write-transaction scope: local serialization + cluster inodelk.

        ``start``/``end`` bound the locked byte range (end exclusive,
        -1 = EOF).  Writes use the full range; heal uses one window per
        txn so writers interleave between windows (ec-heal.c:251)."""

        def __init__(self, ec: "DisperseLayer", loc: Loc, gfid: bytes,
                     ltype: str = "wr", start: int = 0, end: int = -1,
                     fetch: bool = False):
            self.ec = ec
            self.loc = loc
            self.gfid = gfid
            self.ltype = ltype
            self.start = start
            self.end = end
            self.locked: list[int] = []
            # lock-and-fetch: grants carry the inode's xattrs so the
            # caller's metadata wave folds into the lock wave
            self.fetched: dict[int, dict] = {} if fetch else None
            self.local = ltype == "wr" or ec._locks_supported is False
            # Per-transaction lk-owner (reference frame->root->lk_owner):
            # with a per-client owner this client's reads would never
            # conflict with its own in-flight writes brick-side and could
            # decode a mix of old and new fragments mid-write.
            from ..core.iatt import gfid_new as _g

            self.owner = _g()

        async def __aenter__(self):
            if self.local:
                await self.ec._lock(self.gfid).acquire()
                # Flush any eager window NOW, while holding the local
                # lock, before winding our own inodelk: the window holds
                # a conflicting brick lock whose deferred drain needs
                # the local lock we hold — waiting on the brick lock
                # here would deadlock until the lock timeout (and no new
                # window can open while we hold the local lock).
                if self.gfid in self.ec._eager:
                    await self.ec._eager_flush(self.loc, self.gfid)
            try:
                self.locked = await self.ec._inodelk_wind(
                    self.loc, self.ltype, self.owner, self.start,
                    self.end, collect=self.fetched)
            except BaseException:
                if self.local:
                    self.ec._lock(self.gfid).release()
                raise
            if not self.locked and not self.local:
                # no brick-side locks available: fall back to local mutex
                self.local = True
                await self.ec._lock(self.gfid).acquire()
            return self

        async def __aexit__(self, *exc):
            await self.ec._inodelk_unwind(self.loc, self.locked,
                                          self.owner, self.start,
                                          self.end)
            if self.local:
                self.ec._lock(self.gfid).release()
            return False

    # -- eager lock window (ec-common.c:2176 ec_lock_reuse + delayed
    # post-op ec-common.c:2377) ---------------------------------------------

    async def _eager_begin(self, loc: Loc, gfid: bytes) -> _EagerState:
        """Open (or join) the eager window.  Caller holds the local gfid
        lock.  First entry pays the inodelk + metadata fan-out; joiners
        pay nothing."""
        st = self._eager.get(gfid)
        if st is not None:
            if st.timer is not None:
                st.timer.cancel()
                st.timer = None
            return st
        owner = gfid_new()
        fetched: dict[int, dict] = {}
        locked = await self._inodelk_wind(loc, "wr", owner,
                                          collect=fetched)
        try:
            if locked and set(self._up_idx()) <= set(fetched):
                # lock-and-fetch covered every up child: the lock wave
                # WAS the metadata wave
                candidates, size = self._pick_meta(
                    {i: self._parse_meta(r) for i, r in fetched.items()})
            else:
                candidates, size = await self._read_meta(loc)
        except BaseException:
            await self._inodelk_unwind(loc, locked, owner)
            raise
        st = _EagerState(owner, locked, candidates, size,
                         set(self._up_idx()),
                         asyncio.get_running_loop().time())
        self._eager[gfid] = st
        return st

    async def _eager_end(self, loc: Loc, gfid: bytes) -> None:
        """Leave the window: flush now (eager-lock off, or the max-hold
        cap reached) or arm the deferred release timer.  Caller holds
        the local gfid lock."""
        st = self._eager.get(gfid)
        if st is None:
            return
        loop = asyncio.get_running_loop()
        clean = st.delta == 0 and not st.pre
        timeout = 0
        if self.opts["eager-lock"]:
            timeout = self.opts["other-eager-lock-timeout"] if clean \
                else self.opts["eager-lock-timeout"]
        if timeout <= 0 or \
                loop.time() - st.opened >= self.opts["eager-lock-max-hold"]:
            await self._eager_flush(loc, gfid)
            return
        if st.timer is not None:
            st.timer.cancel()
        st.timer = loop.call_later(timeout, self._eager_timer_cb, loc, gfid)

    def _eager_timer_cb(self, loc: Loc, gfid: bytes) -> None:
        """Timer fired: drain in a task we keep a strong reference to
        (the loop holds pending tasks only weakly — an unreferenced
        flush task could be garbage-collected mid-flight, leaking the
        cluster lock)."""
        t = asyncio.get_event_loop().create_task(
            self._eager_drain(loc, gfid))
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    async def _eager_drain(self, loc: Loc, gfid: bytes) -> None:
        """Take the local lock and flush the window (timer path, and any
        fop that needs committed counters: fsync/heal/truncate)."""
        if gfid not in self._eager:
            return
        async with self._lock(gfid):
            await self._eager_flush(loc, gfid)

    async def _quiesce_writes(self, st: _EagerState) -> None:
        """Wait out in-flight parallel write waves.  Callers hold the
        local gfid lock, so no NEW wave can register while we wait
        (registration needs that lock); completion is lock-free."""
        while st.ranges:
            await next(iter(st.ranges.values()))[2]
        while st.inflight:
            await st.idle.wait()

    async def _eager_flush(self, loc: Loc, gfid: bytes) -> None:
        """Commit the delayed post-op in ONE mixed xattrop (version
        add64 + size set + dirty release, atomic on each brick) and drop
        the cluster lock.  Dirty is released only when every brick took
        every write in the window.  Caller holds the local gfid lock."""
        st = self._eager.get(gfid)
        if st is None:
            return
        if st.timer is not None:
            st.timer.cancel()
            st.timer = None
        # quiesce parallel-writes waves first: the post-op must describe
        # a settled window.  New waves can't start — registration needs
        # the gfid lock we hold; removal is lock-free so they can drain.
        await self._quiesce_writes(st)
        self._eager.pop(gfid, None)
        # commit gfid-addressed, NOT by the window-open path: a rename
        # while the post-op was deferred makes that path a lie, and the
        # per-child ENOENTs would silently strand the size/version
        # commit (the file then reads as empty forever — the chaos
        # harness caught exactly this through the gateway's temp+rename
        # PUT).  The reference never has this problem because its
        # xattrop addresses the inode; gfid is our inode identity.
        if gfid:
            loc = Loc("", gfid=gfid)
        unlocked: set[int] = set()
        try:
            post: dict = {}
            if st.delta:
                post[XA_VERSION] = ["add64", _pack_u64x2(st.delta, 0)]
                post[XA_SIZE] = ["set", struct.pack(">Q", st.size)]
            if st.pre and st.good == st.pre and len(st.good) == self.n:
                post[XA_DIRTY] = ["add64",
                                  _pack_u64x2(-1 & 0xFFFFFFFFFFFFFFFF, 0)]
            targets = sorted(st.good & set(self._up_idx()))
            if post and targets:
                # compound unlock: the brick releases this window's
                # inodelk right after committing the post-op (handled by
                # features/locks) — one wave instead of two per window
                lockset = set(st.locked)
                xd = {"unlock-inodelk": ["ec.transaction", "wr", 0, -1,
                                         st.owner]}
                res = await self._dispatch(
                    targets, "xattrop",
                    lambda i: ((loc, "mixed", dict(post)),
                               {"xdata": dict(xd)}
                               if i in lockset else {}))
                unlocked = {i for i, r in res.items()
                            if i in lockset
                            and not isinstance(r, BaseException)}
        finally:
            rest = [i for i in st.locked if i not in unlocked]
            await self._inodelk_unwind(loc, rest, st.owner)

    async def _eager_drain_fd(self, fd: FdObj, force: bool = True) -> None:
        if fd.gfid in self._eager:
            if not force:
                # flush/release are NOT durability points: the delayed
                # post-op outlives them and commits on the deferred-
                # release timer (reference post-op-delay + ec_lock_reuse
                # semantics — the lock and pending xattrop persist past
                # the fop, dropping on timeout/contention; a crash in
                # the window leaves dirty set and heal settles it).
                # This keeps the commit wave off the close latency path
                # and lets an immediate re-open join the live window.
                # fsync (and _Txn entry) still force the drain.
                loc = Loc(fd.path, gfid=fd.gfid)
                async with self._lock(fd.gfid):
                    await self._eager_end(loc, fd.gfid)
                return
            await self._eager_drain(Loc(fd.path, gfid=fd.gfid), fd.gfid)

    # -- dispatch + combine (ec-common.c:816-900, ec-combine.c) ------------

    @property
    def _local_children(self) -> bool:
        """True when no child subtree crosses a wire: awaiting them in
        sequence costs nothing in latency (same event loop does all the
        work anyway) and skips one task creation + wakeup per child per
        wave — a measurable share of the smallfile budget.  Wire
        children keep the concurrent gather so RTTs overlap."""
        cached = getattr(self, "_local_cached", None)
        if cached is None:
            from ..core.layer import walk

            cached = all(l.type_name != "protocol/client"
                         for ch in self.children for l in walk(ch))
            self._local_cached = cached
        return cached

    async def _dispatch(self, idxs: list[int], op: str, argfn):
        """Run fop on children idxs concurrently; returns {idx: result or
        exception}.  argfn(i) -> (args, kwargs) per child."""
        return await self._dispatch_multi(
            {i: (op, *argfn(i)) for i in idxs}, order=idxs)

    async def _dispatch_multi(self, wave: dict[int, tuple],
                              order: list[int] | None = None):
        """Concurrent dispatch with a (possibly) DIFFERENT fop per
        child: ``wave[i] = (op, args, kwargs)`` — the delta write
        path's one mixed wave of data-slice writev + parity xorv, and
        the engine under :meth:`_dispatch`."""
        idxs = sorted(wave) if order is None else order
        if self._local_children:
            out = {}
            for i in idxs:
                op, args, kwargs = wave[i]
                try:
                    out[i] = await getattr(self.children[i], op)(*args,
                                                                 **kwargs)
                except Exception as e:
                    out[i] = e
            return out

        async def one(i):
            op, args, kwargs = wave[i]
            return await getattr(self.children[i], op)(*args, **kwargs)

        results = await asyncio.gather(*(one(i) for i in idxs),
                                       return_exceptions=True)
        return dict(zip(idxs, results))

    def _combine(self, res: dict, min_ok: int | None = None):
        """Pick the quorum answer: enough successes -> representative
        result + list of good indices; else raise the most common error
        (ec_fop_prepare_answer semantics)."""
        min_ok = self.k if min_ok is None else min_ok
        good = {i: r for i, r in res.items()
                if not isinstance(r, BaseException)}
        if len(good) >= min_ok:
            return good
        errs = [r.err for r in res.values() if isinstance(r, FopError)]
        if errs:
            raise FopError(Counter(errs).most_common(1)[0][0],
                           f"{len(good)}/{len(res)} children succeeded")
        for r in res.values():
            if isinstance(r, BaseException):
                raise r
        raise FopError(errno.EIO, "quorum failure")

    # -- xattr counters ----------------------------------------------------

    @staticmethod
    def _parse_meta(r: dict) -> dict:
        return {
            "version": _u64x2(r.get(XA_VERSION)),
            "size": struct.unpack(
                ">Q", r.get(XA_SIZE, b"\0" * 8).ljust(8, b"\0"))[0],
            "dirty": _u64x2(r.get(XA_DIRTY)),
        }

    async def _get_meta(self, idxs, loc: Loc):
        """Per-child (version, size, dirty) from xattrs."""
        res = await self._dispatch(idxs, "getxattr", lambda i: ((loc, None), {}))
        return {i: (r if isinstance(r, BaseException)
                    else self._parse_meta(r))
                for i, r in res.items()}

    async def _xattrop(self, idxs, loc: Loc, deltas: dict[str, bytes]):
        return await self._dispatch(
            idxs, "xattrop", lambda i: ((loc, "add64", dict(deltas)), {}))

    # -- size helpers ------------------------------------------------------

    @staticmethod
    def _vote_size(values) -> int | None:
        """Most-common decoded trusted.ec.size among raw xattr values
        (ONE copy of the unpack + vote semantics for every caller)."""
        sizes = [struct.unpack(">Q", v.ljust(8, b"\0"))[0]
                 for v in values]
        if not sizes:
            return None
        return Counter(sizes).most_common(1)[0][0]

    async def _true_size(self, loc: Loc, idxs=None) -> int:
        idxs = idxs if idxs is not None else self._up_idx()
        res = await self._dispatch(idxs, "getxattr",
                                   lambda i: ((loc, XA_SIZE), {}))
        vote = self._vote_size(
            r[XA_SIZE] for r in res.values()
            if not isinstance(r, BaseException) and XA_SIZE in r)
        return 0 if vote is None else vote

    def _frag_len(self, nbytes: int) -> int:
        """Fragment bytes covering nbytes of user data (stripe padded)."""
        stripes = (nbytes + self.stripe - 1) // self.stripe
        return stripes * CHUNK

    # -- fd plumbing -------------------------------------------------------

    def _child_fd(self, fd: FdObj, i: int) -> FdObj:
        ctx: ECFdCtx | None = fd.ctx_get(self)
        if ctx is None or ctx.child_fds.get(i) is None:
            # anonymous child fd by gfid (reference anonymous fds)
            return FdObj(fd.gfid, fd.flags, path=fd.path, anonymous=True)
        return ctx.child_fds[i]

    # -- namespace fops: dispatch-all + combine ----------------------------

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        # ask every child to piggyback its xattrs on the reply: the
        # true-size vote then needs no second fan-out (the reference
        # loads trusted.ec.* through lookup's dict_t request keys,
        # ec-generic.c ec_lookup)
        xd_req = dict(xdata or {})
        xd_req["get-xattrs"] = True
        res = await self._dispatch(self._up_idx(), "lookup",
                                   lambda i: ((loc, xd_req), {}))
        good = self._combine(res)
        ia, xd = next(iter(good.values()))
        ia = Iatt(**{**ia.__dict__})
        if ia.ia_type is IAType.REG:
            st = self._eager.get(ia.gfid)
            # an open eager window caches the authoritative size (the
            # size xattr commit is deferred to window close)
            if st is not None:
                ia.size = st.size
            else:
                vote = self._vote_size(
                    r[1][XA_SIZE] for r in good.values()
                    if isinstance(r[1], dict) and XA_SIZE in r[1])
                ia.size = vote if vote is not None \
                    else await self._true_size(loc, list(good))
        if isinstance(xd, dict) and xd:
            # the piggybacked counters are EC-internal: never leak
            # trusted.ec.* into upper caches / user-visible xattrs
            xd = {k: v for k, v in xd.items()
                  if not k.startswith("trusted.ec.")}
        return ia, xd

    async def stat(self, loc: Loc, xdata: dict | None = None):
        ia, _ = await self.lookup(loc, xdata)
        return ia

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        loc = Loc(fd.path, gfid=fd.gfid)
        return await self.stat(loc, xdata)

    async def _dispatch_all_simple(self, op: str, *args, **kw):
        res = await self._dispatch(self._up_idx(), op,
                                   lambda i: (args, kw))
        good = self._combine(res)
        return next(iter(good.values()))

    async def mkdir(self, loc: Loc, mode: int = 0o755,
                    xdata: dict | None = None):
        from ..core.iatt import gfid_new

        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())  # same gfid on all bricks
        return await self._dispatch_all_simple("mkdir", loc, mode, xdata)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        return await self._dispatch_all_simple("unlink", loc, xdata)

    async def rmdir(self, loc: Loc, flags: int = 0,
                    xdata: dict | None = None):
        return await self._dispatch_all_simple("rmdir", loc, flags, xdata)

    async def rename(self, oldloc: Loc, newloc: Loc,
                     xdata: dict | None = None):
        return await self._dispatch_all_simple("rename", oldloc, newloc, xdata)

    async def symlink(self, target: str, loc: Loc, xdata: dict | None = None):
        from ..core.iatt import gfid_new

        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        return await self._dispatch_all_simple("symlink", target, loc, xdata)

    async def readlink(self, loc: Loc, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx()[:1], "readlink",
                                   lambda i: ((loc, xdata), {}))
        good = self._combine(res, min_ok=1)
        return next(iter(good.values()))

    async def link(self, oldloc: Loc, newloc: Loc, xdata: dict | None = None):
        return await self._dispatch_all_simple("link", oldloc, newloc, xdata)

    async def mknod(self, loc: Loc, mode: int = 0o644, rdev: int = 0,
                    xdata: dict | None = None):
        from ..core.iatt import gfid_new

        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        return await self._dispatch_all_simple("mknod", loc, mode, rdev, xdata)

    async def setattr(self, loc: Loc, attrs: dict, valid: int = 0,
                      xdata: dict | None = None):
        return await self._dispatch_all_simple("setattr", loc, attrs, valid,
                                               xdata)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        if any(k.startswith("trusted.ec.") for k in xattrs):
            raise FopError(errno.EPERM, "reserved xattr namespace")
        return await self._dispatch_all_simple("setxattr", loc, xattrs,
                                               flags, xdata)

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "getxattr",
                                   lambda i: ((loc, name), {}))
        good = self._combine(res, min_ok=1)
        out = next(iter(good.values()))
        # hide internal accounting (reference filters trusted.ec.*)
        return {k: v for k, v in out.items()
                if not k.startswith("trusted.ec.")} if name is None else out

    async def removexattr(self, loc: Loc, name: str,
                          xdata: dict | None = None):
        if name.startswith("trusted.ec."):
            raise FopError(errno.EPERM, "reserved xattr namespace")
        return await self._dispatch_all_simple("removexattr", loc, name, xdata)

    async def statfs(self, loc: Loc, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "statfs",
                                   lambda i: ((loc, xdata), {}))
        good = self._combine(res, min_ok=1)
        # capacity = min over bricks, scaled by K (user bytes per frag byte)
        agg = min(good.values(), key=lambda s: s["bavail"] * s["bsize"])
        out = dict(agg)
        out["blocks"] *= self.k
        out["bfree"] *= self.k
        out["bavail"] *= self.k
        return out

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "opendir",
                                   lambda i: ((loc, xdata), {}))
        good = self._combine(res)
        fd = FdObj(next(iter(good.values())).gfid, path=loc.path)
        fd.ctx_set(self, ECFdCtx(dict(good), 0))
        return fd

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        # one subvol serves readdir (reference ec-dir-read.c)
        for i in self._up_idx():
            try:
                return await self.children[i].readdir(
                    self._child_fd(fd, i), size, offset, xdata)
            except FopError:
                continue
        raise FopError(errno.ENOTCONN, "no child for readdir")

    async def readdirp(self, fd: FdObj, size: int = 0, offset: int = 0,
                       xdata: dict | None = None):
        entries = await self.readdir(fd, size, offset, xdata)
        out = []
        base = fd.path.rstrip("/")
        for name, _ in entries:
            try:
                ia = await self.stat(Loc(f"{base}/{name}"))
            except FopError:
                ia = None
            out.append((name, ia))
        return out

    # -- open/create -------------------------------------------------------

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        from ..core.iatt import gfid_new

        import os as _os

        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        # counters ride the create itself (storage/posix init-xattrs):
        # one wave instead of create + setxattr
        xdata["init-xattrs"] = {
            XA_VERSION: _pack_u64x2(0, 0),
            XA_SIZE: struct.pack(">Q", 0),
            XA_DIRTY: _pack_u64x2(0, 0)}
        # compound lock-on-create (O_EXCL only: the file and its fresh
        # gfid are born with this fop, so the non-blocking grant cannot
        # conflict): the eager window opens WITH the create — the first
        # write then pays only the fragment wave
        # only once brick-side locks are KNOWN present (first txn
        # probes them): on a lockless graph the compound key would pass
        # through storage untouched and the window would believe in
        # locks nobody holds
        owner = None
        if self.opts["eager-lock"] and flags & _os.O_EXCL and \
                self._locks_supported:
            owner = gfid_new()
            xdata["lock-inodelk"] = ["ec.transaction", "wr", 0, -1,
                                     owner]
        idxs = self._up_idx()
        res = await self._dispatch(idxs, "create",
                                   lambda i: ((loc, flags, mode, xdata), {}))
        try:
            good = self._combine(res, min_ok=self._write_quorum())
        except BaseException:
            if owner is not None:
                # below quorum: the bricks whose create DID land hold
                # our compound-granted whole-file lock — unwind it or
                # it outlives this failed create forever (the winner of
                # a racing O_EXCL create would then hang on it)
                ok = [i for i, r in res.items()
                      if not isinstance(r, BaseException)]
                await self._inodelk_unwind(
                    Loc(loc.path, gfid=xdata["gfid-req"]), ok, owner)
            raise
        child_fds = {i: r[0] for i, r in good.items()}
        ia = next(iter(good.values()))[1]
        fd = FdObj(ia.gfid, flags, path=loc.path)
        fd.ctx_set(self, ECFdCtx(child_fds, flags))
        if owner is not None:
            gfid = ia.gfid
            async with self._lock(gfid):
                if gfid not in self._eager:
                    locked = sorted(good)
                    self._eager[gfid] = _EagerState(
                        owner, locked, locked, 0, set(good),
                        asyncio.get_running_loop().time())
                    await self._eager_end(Loc(loc.path, gfid=gfid),
                                          gfid)
        return fd, ia

    async def open(self, loc: Loc, flags: int = 0, xdata: dict | None = None):
        idxs = self._up_idx()
        res = await self._dispatch(idxs, "open",
                                   lambda i: ((loc, flags), {}))
        good = self._combine(res)
        fd = FdObj(next(iter(good.values())).gfid, flags, path=loc.path)
        fd.ctx_set(self, ECFdCtx(dict(good), flags))
        return fd

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        """Close-path flush: every data wave in this framework is
        synchronous (errors were already reported per-write), and the
        reference's delayed post-op deliberately OUTLIVES flush
        (post-op-delay) — so flush neither fans out to bricks (posix
        flush is a no-op, reference posix_flush returns 0) nor forces
        the commit wave; it just re-arms the deferred release.  fsync
        is the durability point that forces the drain."""
        await self._eager_drain_fd(fd, force=False)
        return {}

    async def fsync(self, fd: FdObj, datasync: int = 0,
                    xdata: dict | None = None):
        await self._eager_drain_fd(fd)  # durability point: commit post-op
        idxs = self._up_idx()
        res = await self._dispatch(
            idxs, "fsync", lambda i: ((self._child_fd(fd, i), datasync), {}))
        self._combine(res)
        return {}

    async def release(self, fd: FdObj):
        # dirty windows still flush at close (deterministic commit
        # point the tests and heal flows rely on); clean ones defer
        await self._eager_drain_fd(fd, force=False)
        ctx: ECFdCtx | None = fd.ctx_del(self)
        if ctx:
            # one parallel wave, not one round trip per child
            async def one(i, cfd):
                rel = getattr(self.children[i], "release", None)
                if rel:
                    try:
                        await rel(cfd)
                    except Exception:
                        pass

            await asyncio.gather(*(one(i, cfd)
                                   for i, cfd in ctx.child_fds.items()))

    # -- the data path -----------------------------------------------------

    async def _txn_meta(self, txn: "_Txn") -> tuple[list[int], int]:
        """Metadata for a fetch=True transaction: use the xattrs that
        rode the lock grants when every up child answered; fall back to
        the classic metadata wave otherwise."""
        if txn.locked and txn.fetched is not None and \
                set(self._up_idx()) <= set(txn.fetched):
            return self._pick_meta({i: self._parse_meta(r)
                                    for i, r in txn.fetched.items()})
        return await self._read_meta(txn.loc)

    async def _read_meta(self, loc: Loc) -> tuple[list[int], int]:
        """(consistent candidate rows, true size) in ONE metadata fan-out.

        Reads must not mix stale fragments: candidates are the up children
        agreeing on (version, size) (the read-txn source selection,
        reference afr-read-txn.c:94 / ec answer grouping).  Clean bricks
        (dirty == 0) are preferred; if no clean quorum exists the largest
        (version, size) group is used regardless of dirty — matching the
        reference's degraded behavior after an unresolved partial write."""
        ups = self._up_idx()
        meta = await self._get_meta(ups, loc)
        vals = {i: m for i, m in meta.items()
                if not isinstance(m, BaseException)}
        return self._pick_meta(vals)

    def _pick_meta(self, vals: dict[int, dict]) -> tuple[list[int], int]:
        if not vals:
            raise FopError(errno.ENOTCONN, "no readable children")
        clean = {i: m for i, m in vals.items() if m["dirty"] == (0, 0)}
        pool = clean if len(clean) >= self.k else vals
        best = Counter((m["version"], m["size"])
                       for m in pool.values()).most_common(1)[0][0]
        rows = [i for i, m in pool.items()
                if (m["version"], m["size"]) == best]
        return rows, best[1]

    def _read_children(self, candidates: list[int], gfid: bytes = b"",
                       mask: bool = False) -> list[int]:
        """Pick K children per read-policy (ec.c read-policy option).
        With ``mask`` the operator's read-mask restricts the set
        (strict, like fop->mask &= ec->read_mask at dispatch) — but
        only inode-READ fops pass it (ec-inode-read.c:1375): a write's
        internal RMW reads and heal reconstruction must never be
        failed by a read-tuning knob."""
        if mask and self._read_mask is not None:
            candidates = [i for i in candidates if i in self._read_mask]
        if len(candidates) < self.k:
            raise FopError(errno.ENOTCONN,
                           f"only {len(candidates)}/{self.n} consistent "
                           f"children, need {self.k}")
        if self.opts["systematic"]:
            # data rows ARE the bytes: when all k survive, the read is
            # a pure reassembly — no decode on any backend, no device
            # round trip on the TPU route.  Spreading load over parity
            # bricks (read-policy) would buy balance at the price of a
            # reconstruction per read; the systematic layout exists to
            # avoid exactly that
            data_rows = [i for i in candidates if i < self.k]
            if len(data_rows) == self.k:
                return data_rows
        policy = self.opts["read-policy"]
        if policy == "first-k":
            return candidates[: self.k]
        if policy == "gfid-hash" and gfid:
            start = int.from_bytes(gfid[-4:], "big") % len(candidates)
        else:  # round-robin
            self._rr = (self._rr + 1) % len(candidates)
            start = self._rr
        rot = candidates[start:] + candidates[:start]
        return sorted(rot[: self.k])

    async def _read_aligned(self, fd: FdObj, a_off: int, a_len: int,
                            candidates: list[int] | None = None,
                            mask: bool = False) -> np.ndarray:
        """Read+decode an aligned region [a_off, a_off+a_len); fragment
        files shorter than the range zero-fill (sparse tails).  ``mask``
        only for user-facing reads (see _read_children)."""
        if a_len == 0:
            return np.zeros(0, dtype=np.uint8)
        f_off = a_off // self.k
        f_len = a_len // self.k
        if candidates is None:
            candidates, _ = await self._read_meta(Loc(fd.path, gfid=fd.gfid))
        excluded: set[int] = set()
        last_err: FopError | None = None
        for _ in range(1 + self.r):  # retry with failing bricks excluded
            avail = [i for i in candidates if i not in excluded]
            rows = self._read_children(avail, fd.gfid, mask=mask)
            res = await self._dispatch(
                rows, "readv",
                lambda i: ((self._child_fd(fd, i), f_len, f_off), {}))
            good = {i: r for i, r in res.items()
                    if not isinstance(r, BaseException)}
            if len(good) < self.k:
                last_err = FopError(errno.EIO, "fragment reads failed")
                # exclude failing bricks for this fop only (transient
                # errors must not poison the up mask; CHILD_DOWN handles
                # real outages)
                excluded.update(i for i, r in res.items()
                                if isinstance(r, BaseException))
                continue
            rows_sorted = sorted(good)
            bufs = [wire.as_single_buffer(good[i]) for i in rows_sorted]
            # healthy systematic fan-out: the fragment buffers (wire
            # blob-lane memoryviews) land DIRECTLY in the codec's
            # reassembly — no per-fragment staging copy (ISSUE 3; the
            # reference's ec_readv answer iobrefs feed dispatch the
            # same way)
            fast = self.codec.reassemble(bufs, rows_sorted, f_len)
            if fast is not None:
                self.read_fanout["fast"] += 1
                return fast
            self.read_fanout["staged"] += 1
            frags = np.zeros((self.k, f_len), dtype=np.uint8)
            for j, buf in enumerate(bufs):
                arr = np.frombuffer(buf, dtype=np.uint8)
                frags[j, : arr.size] = arr
            data = await self._codec_decode(frags, rows_sorted)
            return data
        raise last_err or FopError(errno.EIO, "read failed")

    async def _readv_window(self, fd: FdObj, size: int, offset: int,
                            candidates: list[int], true_size: int):
        if offset >= true_size:
            return b""
        size = min(size, true_size - offset)
        a_off = offset // self.stripe * self.stripe
        end = offset + size
        a_end = (end + self.stripe - 1) // self.stripe * self.stripe
        data = await self._read_aligned(fd, a_off, a_end - a_off,
                                        list(candidates), mask=True)
        # a VIEW of the decoded array, not .tobytes(): the answer rides
        # the stack (and /dev/fuse, via writev) without another copy —
        # the view pins the decode buffer, which lives exactly as long
        # as the caller holds the data
        data = np.ascontiguousarray(data, dtype=np.uint8)
        return memoryview(data)[offset - a_off: offset - a_off + size]

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        loc = Loc(fd.path, gfid=fd.gfid)
        if self.opts["eager-lock"] and self.opts["other-eager-lock"]:
            # reads share the eager window (disperse.other-eager-lock):
            # the first read on an inode pays one lock-and-fetch wave,
            # consecutive reads pay ONLY the fragment wave — without
            # this every kernel-readahead chunk through the mount costs
            # lock + meta + unlock waves of pure latency.  Same-inode
            # ops serialize on the local gfid lock (the reference
            # chains same-inode fops on the lock owner too).
            while True:
                async with self._lock(fd.gfid):
                    st = await self._eager_begin(loc, fd.gfid)
                    # a parallel write mid-dispatch over our range could
                    # hand us a torn stripe (half old, half new
                    # fragments) — wait it out like a conflicting write
                    a_off = offset // self.stripe * self.stripe
                    a_end = (offset + size + self.stripe - 1) \
                        // self.stripe * self.stripe
                    blocker = st.conflict(a_off, a_end)
                    if blocker is None:
                        try:
                            return await self._readv_window(
                                fd, size, offset, st.candidates, st.size)
                        finally:
                            await self._eager_end(loc, fd.gfid)
                await blocker
        async with self._Txn(self, loc, fd.gfid, "rd",
                             fetch=True) as txn:
            candidates, true_size = await self._txn_meta(txn)
            return await self._readv_window(fd, size, offset, candidates,
                                            true_size)

    # one coalesced fan-out must stay a sane allocation: chains whose
    # union range exceeds this decompose normally (read-ahead windows
    # are <= a few MiB; this is an abuse bound, not a tuning knob)
    COALESCE_MAX = 16 << 20

    def _coalescable_readvs(self, links):
        """(fd, [(size, offset), ...], lo, hi) when every link of the
        chain is a readv on ONE fd and their stripe-aligned ranges
        tile a single contiguous region — else None.

        This is ROADMAP item 7: the demand+window chains read-ahead
        emits (readv+readv, one wire frame) decompose at this layer
        into SEPARATE fragment fan-outs, so adjacent stripe reads hit
        the same brick as two readvs.  Merged, each brick serves ONE
        ranged fragment read per fan-out (the disperse read analog of
        write-behind aggregation)."""
        if len(links) < 2:
            return None
        fd = None
        spans = []
        for fop, args, kwargs in links:
            if fop != "readv" or len(args) < 3:
                return None
            lfd, size, offset = args[0], args[1], args[2]
            if not isinstance(lfd, FdObj) or \
                    not isinstance(size, int) or \
                    not isinstance(offset, int) or size < 0 or offset < 0:
                return None
            if fd is None:
                fd = lfd
            elif lfd is not fd and (lfd.gfid != fd.gfid or not fd.gfid):
                return None
            spans.append((size, offset))
        spans_sorted = sorted(spans, key=lambda s: s[1])
        lo = spans_sorted[0][1] // self.stripe * self.stripe
        hi = 0
        cur_end = lo
        for size, offset in spans_sorted:
            a_off = offset // self.stripe * self.stripe
            a_end = (offset + size + self.stripe - 1) \
                // self.stripe * self.stripe
            if a_off > cur_end:
                return None  # a hole: two fan-outs are cheaper
            cur_end = max(cur_end, a_end)
            hi = max(hi, offset + size)
        if hi - lo > self.COALESCE_MAX:
            return None
        return fd, spans, lo, hi

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Chains of adjacent readvs (read-ahead's demand+window frame)
        merge into ONE ranged fragment fan-out: one lock/meta wave, one
        readv per brick covering the union, per-link answers sliced as
        views of the single decode.  Anything else decomposes through
        the normal per-fop path."""
        from ..rpc import compound as cfop

        try:
            links_v = cfop.validate(links)
        except FopError:
            return await super().compound(links, xdata)
        merged = self._coalescable_readvs(links_v)
        if merged is None:
            return await super().compound(links, xdata)
        fd, spans, lo, hi = merged
        # per-link piggybacks (the demand link's xdata carries the
        # trace span) must not vanish when the chain merges: the first
        # link's xdata rides the union fan-out — one dispatch, one
        # span, same propagation the decomposed path would give the
        # demand readv
        xd = next((kw.get("xdata") for _f, _a, kw in links_v
                   if kw.get("xdata")), None)
        try:
            data = await self.readv(fd, hi - lo, lo, xdata=xd)
        except FopError as e:
            # decompose semantics: first link errs, the rest skip
            return [["err", e]] + [["skip", None]] * (len(spans) - 1)
        self.read_coalesced["chains"] += 1
        self.read_coalesced["links"] += len(spans)
        if isinstance(data, wire.SGBuf):  # single join, then slice
            view = memoryview(data.tobytes())
        elif isinstance(data, memoryview):
            view = data
        else:
            view = memoryview(data)
        out = []
        for size, offset in spans:
            start = offset - lo
            if start >= len(view):
                out.append(["ok", b""])
            else:
                out.append(["ok", view[start: start + size]])
        return out

    async def _window_op(self, fd: FdObj, loc: Loc, st: _EagerState,
                         op: str, argfn) -> dict:
        """One write-class wave through the open eager window: pre-op
        once per window, poison-across-dispatch (a torn-off wave must
        never let the flush release dirty over diverged fragments),
        good-set intersection, quorum, version delta."""
        targets = sorted(st.good & set(self._up_idx()))
        if not st.pre:
            # pre-op once per window: dirty+1 (ec-common.c:2377).  For
            # the common case — first fop is a write and every pre
            # target is in the wave — the marker rides the write itself
            # (compound pre-xattrop, applied brick-side before the
            # data), saving one full fan-out wave per window
            pre_targets = sorted(st.good)
            if op == "writev" and pre_targets == targets:
                base = argfn

                def argfn(i, _b=base):
                    args, kw = _b(i)
                    xd = dict(kw.get("xdata") or {})
                    xd["pre-xattrop"] = {XA_DIRTY: _pack_u64x2(1, 0)}
                    return args, {**kw, "xdata": xd}
            else:
                await self._xattrop(pre_targets, loc,
                                    {XA_DIRTY: _pack_u64x2(1, 0)})
            st.pre = set(pre_targets)
        st.inflight += 1
        st.idle.clear()
        ok: set[int] | None = None
        try:
            res = await self._dispatch(targets, op, argfn)
            ok = {i for i, r in res.items()
                  if not isinstance(r, BaseException)}
        finally:
            # a brick that missed ANY wave in the window stays out: it
            # is inconsistent until healed (down bricks miss the wave
            # too — they were never targeted).  A torn-off wave
            # (cancel) poisons its whole target set — the serial path
            # got the same protection by clearing good across the
            # dispatch, but expressed per-wave it survives concurrent
            # parallel-writes waves without clobbering their tracking
            if ok is None:
                st.good -= set(targets)
            else:
                st.good &= ok
            st.inflight -= 1
            if st.inflight == 0:
                st.idle.set()
        if len(ok) < self._write_quorum():
            # surface the bricks' dominant errno (ec_fop_prepare_answer
            # groups answers and picks the most common op_errno) so
            # EDQUOT/ENOSPC reach the caller instead of a generic EIO
            errs = [r.err for r in res.values()
                    if isinstance(r, FopError)]
            err = Counter(errs).most_common(1)[0][0] if errs else errno.EIO
            raise FopError(err,
                           f"{op} quorum lost ({len(ok)}/{self.n})")
        st.delta += 1
        st.candidates = sorted(st.good)
        if st.pre:
            # the dirty mark is committed on the bricks: parallel-writes
            # followers may now dispatch outside the serial first wave
            st.pre_landed.set()
        return {i: r for i, r in res.items() if i in ok}

    # -- parity-delta sub-stripe writes (ISSUE 10) -------------------------

    def _delta_eligible(self, st: _EagerState, data, offset: int) -> bool:
        """May this write take the parity-delta path?  Healthy
        systematic volumes only, write strictly inside the true size,
        unaligned (an aligned write is a pure encode already), key on,
        and no brick has refused xorv.  Everything else keeps the
        full-RMW path byte-identically."""
        if not (data and self.opts["systematic"]
                and self.opts["delta-writes"] and self._xorv_ok):
            return False
        end = offset + len(data)
        if offset % self.stripe == 0 and end % self.stripe == 0:
            return False  # aligned: no RMW to beat
        if end > st.size:
            return False  # EOF-crossing/extending (zero tails, size)
        every = set(range(self.n))
        # a stale fragment XOR'd with a parity delta diverges from the
        # codeword forever: every brick must be up, in the window's
        # good set, and meta-consistent
        return st.good == every and set(st.candidates) == every \
            and all(self.up)

    def _delta_plan(self, data_len: int, offset: int):
        """Map a write [offset, offset+data_len) onto the systematic
        layout: per touched data fragment j, the list of copy pieces
        ``(frag_off, ulo, uhi)`` — user bytes [ulo, uhi) live verbatim
        at fragment byte ``frag_off`` (chunk j of each stripe).  Pieces
        of one fragment tile a single contiguous fragment range (one
        contiguous user interval intersects each 512-byte chunk window
        at most once per stripe, and consecutive stripes are adjacent
        in fragment space)."""
        end = offset + data_len
        a_off = offset // self.stripe * self.stripe
        a_end = (end + self.stripe - 1) // self.stripe * self.stripe
        pieces: dict[int, list[tuple[int, int, int]]] = {}
        for s in range(a_off // self.stripe, a_end // self.stripe):
            base = s * self.stripe
            for j in range(self.k):
                u0 = base + j * CHUNK
                lo, hi = max(u0, offset), min(u0 + CHUNK, end)
                if lo < hi:
                    pieces.setdefault(j, []).append(
                        (s * CHUNK + (lo - u0), lo, hi))
        return a_off, a_end, pieces

    async def _writev_delta(self, fd: FdObj, loc: Loc, st: _EagerState,
                            data: bytes, offset: int):
        """The parity-delta wave: read back ONLY the overwritten bytes
        from the touched data fragments, form Δ = old ⊕ new, then ONE
        wave of touched-data writev + parity xorv(parity(Δ)) — no
        k-fragment decode, no n-fragment rewrite.  Untouched data
        bricks see no fop and KEEP their good status (their chunks did
        not change; the window's post-op version wave still covers
        them).  Rides the same pre-op/good-set/poison/quorum machinery
        as every write wave."""
        end = offset + len(data)
        a_off, a_end, pieces = self._delta_plan(len(data), offset)
        a_len = a_end - a_off
        f_len = a_len // self.k
        intervals: dict[int, tuple[int, int]] = {}
        for j, ps in pieces.items():
            lo = ps[0][0]
            hi = ps[-1][0] + (ps[-1][2] - ps[-1][1])
            if hi - lo != sum(uhi - ulo for _f, ulo, uhi in ps):
                raise _DeltaFallback()  # non-contiguous (cannot happen)
            intervals[j] = (lo, hi)
        span = _tracing.enter(self.name, "delta-write") \
            if _tracing.ENABLED else None
        t0 = _time.perf_counter()
        failed = True
        try:
            # old bytes: one ranged readv per touched data fragment —
            # internal write reads, never subject to the read mask
            res = await self._dispatch(
                sorted(intervals), "readv",
                lambda i: ((self._child_fd(fd, i),
                            intervals[i][1] - intervals[i][0],
                            intervals[i][0]), {}))
            if any(isinstance(r, BaseException) for r in res.values()):
                raise _DeltaFallback()  # read trouble: RMW sorts it out
            newbuf = np.zeros(a_len, dtype=np.uint8)
            newbuf[offset - a_off: end - a_off] = np.frombuffer(
                bytes(data), dtype=np.uint8)
            delta = newbuf.copy()  # becomes old ⊕ new inside the range
            read_bytes = 0
            for j, ps in pieces.items():
                lo = intervals[j][0]
                arr = np.frombuffer(wire.as_single_buffer(res[j]),
                                    dtype=np.uint8)
                read_bytes += intervals[j][1] - lo
                for frag_off, ulo, uhi in ps:
                    piece = arr[frag_off - lo:
                                frag_off - lo + (uhi - ulo)]
                    if piece.size:  # a short tail XORs against zeros
                        delta[ulo - a_off:
                              ulo - a_off + piece.size] ^= piece
            pdeltas = await self._codec_delta(delta)
            f_off = a_off // self.k
            wave: dict[int, tuple] = {}
            data_write_bytes = 0
            for j, ps in pieces.items():
                lo, hi = intervals[j]
                wbuf = np.concatenate(
                    [newbuf[ulo - a_off: uhi - a_off]
                     for _f, ulo, uhi in ps])
                data_write_bytes += wbuf.size
                wave[j] = ("writev", (self._child_fd(fd, j),
                                      wbuf.tobytes(), lo), {})
            for p in range(self.k, self.n):
                wave[p] = ("xorv", (self._child_fd(fd, p),
                                    pdeltas[p - self.k].tobytes(),
                                    f_off), {})
            if not st.pre:
                # pre-op once per window (the writev piggyback does not
                # apply: the wave targets a subset of the pre set)
                pre_targets = sorted(st.good)
                await self._xattrop(pre_targets, loc,
                                    {XA_DIRTY: _pack_u64x2(1, 0)})
                st.pre = set(pre_targets)
            st.inflight += 1
            st.idle.clear()
            ok: set[int] | None = None
            unsupported: set[int] = set()
            res = {}
            try:
                res = await self._dispatch_multi(wave)
                unsupported = {i for i, r in res.items()
                               if isinstance(r, FopError)
                               and r.err == errno.EOPNOTSUPP
                               and wave[i][0] == "xorv"}
                ok = {i for i, r in res.items()
                      if not isinstance(r, BaseException)}
            finally:
                # DELIBERATELY narrower poison than _window_op's
                # `good &= ok`: this wave targets a SUBSET of good, and
                # the untargeted data bricks are still current (their
                # chunks did not change), so only targeted failures
                # drop.  _window_op's full wave targets good∩up, where
                # dropping every non-ok brick (down ones included) is
                # the right call — keep both semantics in view when
                # editing either site.
                if ok is None:
                    st.good -= set(wave)  # torn-off wave: poison all
                else:
                    # an EOPNOTSUPP brick applied NOTHING, and the
                    # immediate full-RMW redo rewrites every fragment
                    # of this region on all good bricks — keep it good
                    st.good -= set(wave) - ok - unsupported
                st.inflight -= 1
                if st.inflight == 0:
                    st.idle.set()
            if unsupported:
                self._xorv_ok = False
                log.warning(3, "%s: brick(s) %s have no xorv (live "
                            "downgrade?) — parity-delta writes off, "
                            "full RMW from here", self.name,
                            sorted(unsupported))
                raise _DeltaFallback()
            # quorum over SURVIVING good bricks, not wave oks: the
            # untargeted data bricks count toward the file's
            # consistent set (under _window_op's full wave the two
            # formulations coincide — targets ARE good∩up there)
            if len(st.good & set(self._up_idx())) < self._write_quorum():
                errs = [r.err for r in res.values()
                        if isinstance(r, FopError)]
                err = Counter(errs).most_common(1)[0][0] if errs \
                    else errno.EIO
                raise FopError(err, f"delta write quorum lost "
                                    f"({len(st.good)}/{self.n})")
            st.delta += 1
            st.candidates = sorted(st.good)
            if st.pre:
                st.pre_landed.set()
            # what the replaced RMW would have moved: a k-fragment
            # aligned-region read + an n-fragment rewrite
            rmw_read = max(
                0, min(a_end, self._frag_len(st.size) * self.k) - a_off)
            self.write_path["delta"] += 1
            o = self.traffic_origin
            self.delta_origin[o] = self.delta_origin.get(o, 0) + 1
            self.delta_saved["read"] += max(0, rmw_read - read_bytes)
            self.delta_saved["write"] += max(
                0, self.n * f_len
                - (data_write_bytes + self.r * f_len))
            ia = next(r for r in res.values()
                      if not isinstance(r, BaseException))
            ia = Iatt(**{**ia.__dict__})
            st.size = max(st.size, end)
            ia.size = st.size
            failed = False
            return ia
        finally:
            if span is not None:
                _tracing.exit_span(span, _time.perf_counter() - t0,
                                   failed)

    async def _writev_in_window(self, fd: FdObj, loc: Loc, st: _EagerState,
                                data: bytes, offset: int,
                                allow_delta: bool = True):
        if allow_delta and self._delta_eligible(st, data, offset):
            try:
                return await self._writev_delta(fd, loc, st, data,
                                                offset)
            except _DeltaFallback:
                pass  # downgraded peer / read trouble: full RMW below
        true_size = st.size
        end = offset + len(data)
        a_off = offset // self.stripe * self.stripe
        a_end = (end + self.stripe - 1) // self.stripe * self.stripe
        buf = np.zeros(a_end - a_off, dtype=np.uint8)
        # RMW: pull existing stripes overlapping the aligned region
        if true_size > a_off and (offset % self.stripe or
                                  end % self.stripe or
                                  offset > true_size):
            have_end = min(a_end, self._frag_len(true_size) * self.k)
            if have_end > a_off:
                self.write_path["rmw"] += 1
                old = await self._read_aligned(
                    fd, a_off, have_end - a_off, list(st.candidates))
                buf[: old.size] = old
                # trim stale bytes beyond true size (padding zeros)
                if true_size - a_off < old.size:
                    buf[max(0, true_size - a_off): old.size] = 0
        buf[offset - a_off: end - a_off] = np.frombuffer(
            bytes(data), dtype=np.uint8)
        frags = await self._codec_encode(buf)
        f_off = a_off // self.k
        good = await self._window_op(
            fd, loc, st, "writev",
            lambda i: ((self._child_fd(fd, i),
                        frags[i].tobytes(), f_off), {}))
        # re-read st.size (not the wave-start snapshot): a concurrent
        # parallel write past our range may have grown it meanwhile
        st.size = max(st.size, end)
        ia = next(iter(good.values()))
        ia = Iatt(**{**ia.__dict__})
        ia.size = st.size
        return ia

    async def writev(self, fd: FdObj, data: bytes, offset: int,
                     xdata: dict | None = None):
        """Write under the eager window: first fop on an inode pays
        inodelk + metadata + pre-op; followers pay only the fragment
        write wave; the combined post-op commits at window close
        (ec-inode-write.c:2141 + ec-common.c:2176,2377).

        parallel-writes (ec.c:284 + ec_is_range_conflict,
        ec-common.c:185): once the window's dirty pre-op has landed,
        writes touching disjoint aligned stripe ranges dispatch
        concurrently — the local gfid lock covers only window
        bookkeeping, not the RMW/encode/write wave itself."""
        loc = Loc(fd.path, gfid=fd.gfid)
        if not self.opts["parallel-writes"]:
            async with self._lock(fd.gfid):
                st = await self._eager_begin(loc, fd.gfid)
                # waves registered before a live parallel-writes->off
                # reconfigure may still be dispatching: settle them
                await self._quiesce_writes(st)
                try:
                    return await self._writev_in_window(fd, loc, st,
                                                        data, offset)
                finally:
                    await self._eager_end(loc, fd.gfid)
        end = offset + len(data)
        a_off = offset // self.stripe * self.stripe
        a_end = (end + self.stripe - 1) // self.stripe * self.stripe
        while True:
            async with self._lock(fd.gfid):
                st = await self._eager_begin(loc, fd.gfid)
                if not st.pre_landed.is_set():
                    # the window's first write runs solo under the lock:
                    # it carries the compound pre-op, and dirty+1 must
                    # be ON the bricks before any concurrent data wave
                    try:
                        return await self._writev_in_window(
                            fd, loc, st, data, offset)
                    finally:
                        await self._eager_end(loc, fd.gfid)
                blocker = st.conflict(a_off, a_end)
                if blocker is None:
                    token = st.add_range(a_off, a_end)
                    break
            await blocker  # overlapping write in flight: wait, retry
        try:
            return await self._writev_in_window(fd, loc, st, data, offset)
        finally:
            st.del_range(token)  # lock-free: wakes conflict waiters
            async with self._lock(fd.gfid):
                await self._eager_end(loc, fd.gfid)

    # -- allocation-class fops (ec-inode-write.c fallocate/discard/
    # zerofill; zeros are a fixed point of the linear code: a zero user
    # stripe encodes to zero fragments, so zero ranges ride the normal
    # write path and fragment holes stay holes) -------------------------

    async def _zero_in_window(self, fd: FdObj, loc: Loc, st: _EagerState,
                              offset: int, length: int) -> None:
        """Zero a user range through the window write path (RMW at the
        stripe edges), in bounded chunks."""
        window = max(self.stripe,
                     int(self.opts["self-heal-window-size"]))
        while length > 0:
            n = min(window, length)
            # allocation-class edges keep the full-RMW path (ISSUE 10
            # fallback matrix): zerofill semantics are size-coupled and
            # the RMW path is their long-proven shape
            await self._writev_in_window(fd, loc, st, b"\0" * n, offset,
                                         allow_delta=False)
            offset += n
            length -= n

    async def fallocate(self, fd: FdObj, mode: int, offset: int,
                        length: int, xdata: dict | None = None):
        """Reserve space; extend the file when FALLOC_FL_KEEP_SIZE (bit
        0) is not set (ec_fallocate, ec-inode-write.c).  Allocation maps
        to KEEP_SIZE fragment-range fallocate on every brick (pure
        allocation: fragment content and sizes never change); the
        extension region past EOF becomes encoded zeros via the window
        write path, all under the inode's lock."""
        if mode & ~1:
            # punch/zero modes carve inside stripes; route them through
            # discard/zerofill, which do the edge RMW (the reference
            # also rejects unsupported fallocate modes, ec_fallocate)
            raise FopError(errno.EOPNOTSUPP,
                           "EC fallocate supports only KEEP_SIZE")
        loc = Loc(fd.path, gfid=fd.gfid)
        async with self._lock(fd.gfid):
            st = await self._eager_begin(loc, fd.gfid)
            await self._quiesce_writes(st)  # settle parallel waves
            try:
                end = offset + length
                f_off = offset // self.stripe * CHUNK
                f_end = (end + self.stripe - 1) // self.stripe * CHUNK
                idxs = self._up_idx()
                res = await self._dispatch(
                    idxs, "fallocate",
                    lambda i: ((self._child_fd(fd, i), mode | 1, f_off,
                                f_end - f_off), {}))
                self._combine(res, min_ok=self._write_quorum())
                if not (mode & 1) and end > st.size:
                    await self._zero_in_window(fd, loc, st, st.size,
                                               end - st.size)
            finally:
                await self._eager_end(loc, fd.gfid)
        ia, _ = await self.lookup(loc)
        return ia

    async def discard(self, fd: FdObj, offset: int, length: int,
                      xdata: dict | None = None):
        """Punch a hole WITHOUT growing the file (ec_discard): the
        stripe-aligned interior punches fragment holes brick-side (child
        discard, O(1) data motion); the unaligned edges re-encode as
        zeros through the window."""
        loc = Loc(fd.path, gfid=fd.gfid)
        async with self._lock(fd.gfid):
            st = await self._eager_begin(loc, fd.gfid)
            await self._quiesce_writes(st)  # settle parallel waves
            try:
                end = min(offset + length, st.size)
                if end > offset:
                    a_lo = (offset + self.stripe - 1) \
                        // self.stripe * self.stripe
                    a_hi = end // self.stripe * self.stripe
                    if a_hi > a_lo:
                        f_off, f_len = a_lo // self.k, (a_hi - a_lo) // self.k
                        await self._window_op(
                            fd, loc, st, "discard",
                            lambda i: ((self._child_fd(fd, i), f_off,
                                        f_len), {}))
                    head_end = min(a_lo, end)
                    if offset < head_end:
                        await self._zero_in_window(fd, loc, st, offset,
                                                   head_end - offset)
                    # a range inside ONE stripe is fully covered by the
                    # head zeroing; start the tail after it
                    tail_start = max(a_hi, head_end)
                    if tail_start < end:
                        await self._zero_in_window(fd, loc, st, tail_start,
                                                   end - tail_start)
            finally:
                await self._eager_end(loc, fd.gfid)
        ia, _ = await self.lookup(loc)
        return ia

    async def zerofill(self, fd: FdObj, offset: int, length: int,
                       xdata: dict | None = None):
        """Zero the range, extending the file if it ends past EOF
        (ec_zerofill)."""
        loc = Loc(fd.path, gfid=fd.gfid)
        async with self._lock(fd.gfid):
            st = await self._eager_begin(loc, fd.gfid)
            await self._quiesce_writes(st)  # settle parallel waves
            try:
                if length > 0:
                    await self._zero_in_window(fd, loc, st, offset, length)
            finally:
                await self._eager_end(loc, fd.gfid)
        ia, _ = await self.lookup(loc)
        return ia

    async def seek(self, fd: FdObj, offset: int, what: str = "data",
                   xdata: dict | None = None):
        """SEEK_DATA/SEEK_HOLE over fragments (ec_seek,
        ec-inode-read.c): ask one consistent brick, scale the fragment
        offset back to user space at stripe granularity — data/holes in
        user space land on the same stripes in every fragment because
        zero stripes encode to zero fragments."""
        loc = Loc(fd.path, gfid=fd.gfid)
        async with self._Txn(self, loc, fd.gfid, "rd"):
            candidates, true_size = await self._read_meta(loc)
            if offset >= true_size:
                raise FopError(errno.ENXIO, "offset beyond EOF")
            f_off = offset // self.stripe * CHUNK
            last: FopError | None = None
            for i in self._read_children(candidates, fd.gfid, mask=True):
                try:
                    r = await self.children[i].seek(
                        self._child_fd(fd, i), f_off, what)
                except FopError as e:
                    if e.err == errno.ENXIO:
                        if what == "data":
                            raise  # no data at/after offset
                        return true_size  # implicit hole at EOF
                    last = e
                    continue
                user = r // CHUNK * self.stripe
                out = max(offset, user)
                return min(out, true_size)
            raise last or FopError(errno.ENOTCONN, "no child for seek")

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        fd = FdObj((await self.lookup(loc))[0].gfid, path=loc.path,
                   anonymous=True)
        return await self.ftruncate(fd, size, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        loc = Loc(fd.path, gfid=fd.gfid)
        async with self._Txn(self, loc, fd.gfid, "wr"):
            candidates, true_size = await self._read_meta(loc)
            a_size = (size + self.stripe - 1) // self.stripe * self.stripe
            tail = b""
            if size < true_size and size % self.stripe:
                # re-encode the final partial stripe zero-padded
                old = await self._read_aligned(
                    fd, a_size - self.stripe, self.stripe, candidates)
                buf = np.zeros(self.stripe, dtype=np.uint8)
                keep = size - (a_size - self.stripe)
                buf[:keep] = old[:keep]
                tail = buf.tobytes()
            idxs = self._up_idx()
            f_size = a_size // self.k
            await self._xattrop(idxs, loc, {XA_DIRTY: _pack_u64x2(1, 0)})
            res = await self._dispatch(
                idxs, "ftruncate",
                lambda i: ((self._child_fd(fd, i), f_size), {}))
            good = [i for i, r in res.items()
                    if not isinstance(r, BaseException)]
            if len(good) < self._write_quorum():
                raise FopError(errno.EIO, "truncate quorum lost")
            if tail:
                frags = await self._codec_encode(
                    np.frombuffer(tail, dtype=np.uint8))
                f_off = (a_size - self.stripe) // self.k
                await self._dispatch(
                    good, "writev",
                    lambda i: ((self._child_fd(fd, i),
                                frags[i].tobytes(), f_off), {}))
            # one atomic mixed xattrop: version +1, size absolute, dirty
            # released only on full participation
            post = {XA_VERSION: ["add64", _pack_u64x2(1, 0)],
                    XA_SIZE: ["set", struct.pack(">Q", size)]}
            if len(good) == self.n:
                post[XA_DIRTY] = ["add64",
                                  _pack_u64x2(-1 & 0xFFFFFFFFFFFFFFFF, 0)]
            await self._dispatch(
                good, "xattrop", lambda i: ((loc, "mixed", dict(post)), {}))
            ia, _ = await self.lookup(loc)
            return ia

    # -- heal (ec-heal.c analog) -------------------------------------------

    async def heal_info(self, loc: Loc) -> dict:
        """Which bricks disagree on version/size (heal candidates).

        Direction logic (reference ec_heal_data_find_direction,
        ec-heal.c:1658): bricks are grouped by (data version, size); the
        source group is the one with the HIGHEST version that still has
        >= K members — never a dirty-but-stale brick that only saw the
        pre-op.  Dirty flags do not disqualify a source: after a partial
        write the surviving bricks keep dirty set on purpose (that is
        what feeds the pending index), yet they hold both the data and
        the post-op version bump."""
        if self._eager:
            # judge committed counters, not an open window's deferred ones
            try:
                gfid = (await self.lookup(loc))[0].gfid
                if gfid in self._eager:
                    await self._eager_drain(Loc(loc.path, gfid=gfid), gfid)
            except FopError:
                pass
        meta = await self._get_meta(list(range(self.n)), loc)
        versions = {}
        for i, m in meta.items():
            if isinstance(m, BaseException):
                versions[i] = None
            else:
                versions[i] = (m["version"], m["size"], m["dirty"])
        ok = {i: v for i, v in versions.items() if v is not None}
        if not ok:
            raise FopError(errno.ENOTCONN, "no bricks reachable")
        groups: dict[tuple, list[int]] = {}
        for i, v in ok.items():
            groups.setdefault((v[0], v[1]), []).append(i)
        viable = [vs for vs, members in groups.items()
                  if len(members) >= self.k]
        good_vs = max(viable) if viable else max(groups)
        good = sorted(groups[good_vs])
        bad = [i for i in range(self.n) if i not in good]
        dirty = any(v[2] != (0, 0) for v in ok.values())
        return {"good": good, "bad": bad, "version": good_vs,
                "per_brick": versions, "dirty": dirty}

    async def heal_file(self, path: str) -> dict:
        """Region-locked re-encode heal: decode from good K, rewrite bad
        fragments, align counters (ec_rebuild_data, ec-heal.c:2048).

        Locking is per heal window, not whole-file (ec_heal_inodelk
        takes offset/size, ec-heal.c:251): direction + file creation run
        under a brief full-range txn, each window rebuild under a txn
        covering only that window's byte range, and the final counter
        alignment under a full-range txn again.  Writers — who lock the
        full range per fop — wait at most one window, so a multi-GiB
        heal never freezes I/O to the file.  This is safe because live
        writes dispatch to ALL up bricks (including the ones being
        healed), so regions the heal already rebuilt stay current; if
        the version moved while healing (a write landed), dirty is left
        set so the next shd pass re-verifies instead of force-clearing
        counters under a concurrent writer."""
        loc = Loc(path)
        info = await self.heal_info(loc)
        good, bad = info["good"], info["bad"]
        if len(good) < self.k:
            raise FopError(errno.EIO,
                           f"unhealable: only {len(good)} good copies")
        if not bad:
            if not info.get("dirty"):
                return {"healed": [], "skipped": True}
            # Dirty with no version skew does NOT mean converged content:
            # a quorum-lost write leaves a mix of old and new fragments
            # behind identical version/size xattrs.  Rebuild the
            # non-source bricks from K sources before releasing dirty —
            # the reference re-runs data heal whenever dirty is set
            # (ec_heal_data, ec-heal.c:2048), never just unmarks.
            bad = good[self.k:]
            good = good[: self.k]
        gfid = (await self.lookup(loc))[0].gfid
        fd = FdObj(gfid, path=path, anonymous=True)
        async with self._Txn(self, loc, gfid, "wr"):
            meta = await self._get_meta(good, loc)
            rep = next((m for m in meta.values()
                        if not isinstance(m, BaseException)), None)
            if rep is None:
                raise FopError(errno.EIO, "heal: no readable source meta")
            true_size = rep["size"]
            version = rep["version"]
            # ensure bad bricks have the file at all
            for i in bad:
                try:
                    await self.children[i].lookup(loc)
                except FopError:
                    try:
                        await self.children[i].mknod(
                            loc, 0o644, 0, {"gfid-req": gfid})
                    except FopError:
                        continue
        window = int(self.opts["self-heal-window-size"])
        window = max(self.stripe, window // self.stripe * self.stripe)
        healed = []
        a_total = self._frag_len(true_size) * self.k
        rows = good[: self.k]
        rows_sorted = sorted(rows)
        from ..features.bit_rot_stub import HEAL_WRITE

        off = 0
        while off < a_total:
            length = min(window, a_total - off)
            # one ranged txn per window: writers (full-range locks)
            # interleave between windows instead of waiting out the
            # whole rebuild
            async with self._Txn(self, loc, gfid, "wr",
                                 start=off, end=off + length):
                f_off, f_len = off // self.k, length // self.k
                res = await self._dispatch(
                    rows, "readv",
                    lambda i: ((self._child_fd(fd, i), f_len, f_off), {}))
                frags_in = np.zeros((self.k, f_len), dtype=np.uint8)
                for j, i in enumerate(rows_sorted):
                    r = res[i]
                    if isinstance(r, BaseException):
                        raise FopError(errno.EIO,
                                       "heal source read failed")
                    b = np.frombuffer(r, dtype=np.uint8)
                    frags_in[j, : b.size] = b
                # heal traffic is tagged so the mesh families (and the
                # MULTICHIP dryrun) can tell shd re-encode from serving
                data = await self._codec_decode(frags_in, rows_sorted,
                                                origin="heal")
                frags_out = await self._codec_encode(data, origin="heal")
                await self._dispatch(
                    bad, "writev",
                    lambda i: ((self._child_fd(fd, i),
                                frags_out[i].tobytes(), f_off),
                               {"xdata": {HEAL_WRITE: True}}))
            off += length
        async with self._Txn(self, loc, gfid, "wr"):
            # counters: re-read under the full lock.  Untouched version
            # -> the heal saw every byte as of `version`: align bad and
            # clear dirty (the pre-region-lock behavior).  Version moved
            # -> writes landed mid-heal; their data DID reach the bad
            # bricks (writes go to all up children) so align version/
            # size to the current good value, but leave dirty for the
            # next shd pass: a write that failed on a brick mid-heal
            # after its window was rebuilt is only detectable there.
            meta2 = await self._get_meta(good, loc)
            rep2 = next((m for m in meta2.values()
                         if not isinstance(m, BaseException)), None)
            if rep2 is None:
                raise FopError(errno.EIO, "heal: source meta lost")
            fix = {XA_VERSION: _pack_u64x2(*rep2["version"]),
                   XA_SIZE: struct.pack(">Q", rep2["size"])}
            stable = rep2["version"] == version
            if stable:
                fix[XA_DIRTY] = _pack_u64x2(0, 0)
            await self._dispatch(bad, "setxattr",
                                 lambda i: ((loc, dict(fix)), {}))
            if stable:
                await self._dispatch(good, "setxattr", lambda i: (
                    (loc, {XA_DIRTY: _pack_u64x2(0, 0)}), {}))
            for i in bad:
                healed.append(i)
            return {"healed": healed, "skipped": False,
                    "size": rep2["size"], "stable": stable}

    async def _codec_encode(self, buf, origin: str | None = None):
        if self._batching:
            return await self.codec.encode_async(
                buf, origin=origin or self.traffic_origin)
        return self.codec.encode(buf)

    async def _codec_delta(self, buf, origin: str | None = None):
        """Parity-rows-only delta encode through the batching window
        (coalesced delta flushes ride the same measured ladder)."""
        if self._batching:
            return await self.codec.encode_delta_async(
                buf, origin=origin or self.traffic_origin)
        return self.codec.encode_delta(buf)

    async def _codec_decode(self, frags, rows,
                            origin: str | None = None):
        if self._batching:
            return await self.codec.decode_async(
                frags, rows, origin=origin or self.traffic_origin)
        return self.codec.decode(frags, rows)

    async def fini(self):
        for gfid in list(self._eager):
            try:
                await self._eager_drain(Loc("", gfid=gfid), gfid)
            except Exception:
                pass
        self.codec.close()
        await super().fini()

    def dump_private(self) -> dict:
        return {
            "fragments": self.k, "redundancy": self.r,
            "stripe_size": self.stripe,
            "backend": self.codec.backend,
            "up": self.up, "up_count": sum(self.up),
            "read_fanout": dict(self.read_fanout),
            "read_coalesced": dict(self.read_coalesced),
            "write_path": dict(self.write_path),
            "delta_saved": dict(self.delta_saved),
            "xorv_ok": self._xorv_ok,
            "eager_windows": len(self._eager),
            "stripe_cache": self.codec.dump_stats(),
        }
