"""cluster/distribute — consistent-hash distribution of the namespace (DHT).

Reference: xlators/cluster/dht (34k LoC).  Behaviors kept:

* **Placement** (dht-hashfn.c:72, dht-layout.c:20-94): a file lives on the
  subvolume whose hash range covers ``hash(basename)``; directories exist
  on every subvolume.  Per-directory hash ranges are PERSISTED in a
  ``trusted.glusterfs.dht`` xattr on each subvolume's copy of the
  directory (written at mkdir, read at first use, cached with a TTL);
  a directory without the xattr falls back to the derived even split.
  ``rebalance fix-layout`` rewrites ranges — optionally weighted — over
  the current child set (dht-selfheal.c layout set + fix-layout), which
  is what lets add-brick direct NEW creates at the new brick without
  lookup-everywhere.
* **Linkto files** (dht-linkfile.c:95): after rename/rebalance, a file
  whose data lives off its hashed subvolume leaves a zero-byte pointer
  file there carrying ``trusted.glusterfs.dht.linkto = <real subvol>``;
  lookup follows it.
* **Global lookup** (dht fan-out lookup): hashed-subvol miss falls back
  to an everywhere-lookup, healing the linkto.
* **Rebalance** (dht-rebalance.c:39 dht_migrate_file): walk files, move
  data to the currently-hashed subvolume, drop linktos.

The hash is a Davies-Meyer-style 32-bit construction over the basename
(same family as the reference's gf_dm_hashfn; exact bit-compat is not
required since layouts are never exchanged with the reference).
"""

from __future__ import annotations

import asyncio
import errno
import os
import struct
import time
from collections import Counter

from ..core.fops import FopError
from ..core.iatt import IAType, gfid_new
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("dht")

XA_LINKTO = "trusted.glusterfs.dht.linkto"
XA_LAYOUT = "trusted.glusterfs.dht"
# packed per-subvol range: (version, commit, start, stop) — the shape of
# the reference's on-disk layout record (dht-layout.c:20-94); commit is
# the layout generation (reference vol_commit_hash): when it matches the
# CURRENT child set, a miss at the range owner is authoritative and the
# everywhere-lookup is skipped (cluster.lookup-optimize semantics)
_LAYOUT_FMT = ">IIII"
LAYOUT_TTL = 5.0  # seconds a cached directory layout stays trusted


def dm_hash(name: str) -> int:
    """Davies-Meyer-style 32-bit hash over the basename."""
    h = 0x9747B28C
    for b in name.encode():
        # one DM round: encrypt h with byte-derived key, xor back in
        k = (b * 0x01000193) & 0xFFFFFFFF
        e = (h ^ k) & 0xFFFFFFFF
        e = (e * 0x85EBCA6B + 0xC2B2AE35) & 0xFFFFFFFF
        e ^= e >> 13
        h = (h ^ e) & 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 0x7FEB352D) & 0xFFFFFFFF
    h ^= h >> 15
    return h


class DhtFdCtx:
    __slots__ = ("idx", "child_fd")

    def __init__(self, idx: int, child_fd: FdObj):
        self.idx = idx
        self.child_fd = child_fd


@register("cluster/distribute")
class DistributeLayer(Layer):
    OPTIONS = (
        Option("lookup-unhashed", "bool", default="on",
               description="fan-out lookup on hashed-subvol miss"),
        Option("min-free-disk", "percent", default=10.0),
        Option("decommissioned", "str", default="",
               description="comma-separated child names leaving the "
               "volume (remove-brick start): excluded from the layout "
               "so no NEW data lands on them while rebalance drains "
               "them (dht decommission_node_map)"),
        Option("lookup-optimize", "bool", default="on",
               description="skip the everywhere-lookup on a miss when "
               "the directory's layout commit matches the current "
               "child set (cluster.lookup-optimize)"),
        Option("rebal-throttle", "enum", default="normal",
               values=("lazy", "normal", "aggressive"),
               description="migrator concurrency for rebalance/drain "
               "(cluster.rebal-throttle, dht-rebalance.c:3269: lazy "
               "yields to client I/O, aggressive saturates); "
               "reconfigurable mid-run"),
        Option("min-free-inodes", "percent", default=5.0,
               description="divert new files off a child whose free "
                           "inode share fell under this "
                           "(cluster.min-free-inodes, "
                           "dht_is_subvol_filled)"),
        Option("readdir-optimize", "bool", default="off",
               description="list DIRECTORY entries only from the first "
                           "up child — dirs exist on every child, the "
                           "other copies are redundant "
                           "(cluster.readdir-optimize; same caveat as "
                           "the reference: a dir missing there until "
                           "heal is briefly not listed)"),
        Option("rsync-hash-regex", "str", default="rsync",
               description="hash this capture instead of the raw name "
                           "('rsync' = the built-in ^\\.(.+)\\.[^.]+$ "
                           "pattern, 'none' = off): rsync temp names "
                           "land where their final name will "
                           "(cluster.rsync-hash-regex, dht extract_"
                           "regex)"),
        Option("extra-hash-regex", "str", default="none",
               description="second rename-pattern capture tried after "
                           "rsync-hash-regex (cluster.extra-hash-regex)"),
        Option("subvols-per-directory", "int", default=0, min=0,
               description="each directory's layout spans only this "
                           "many children, rotated by the path hash "
                           "(cluster.subvols-per-directory; 0 = all): "
                           "bounds per-dir fan-out on very wide "
                           "volumes"),
        Option("weighted-rebalance", "bool", default="on",
               description="fix-layout sizes hash ranges by child "
                           "capacity instead of evenly "
                           "(cluster.weighted-rebalance, "
                           "dht_get_du_info)"),
        Option("rebalance-stats", "bool", default="off",
               description="per-file timing in rebalance status "
                           "(cluster.rebalance-stats)"),
        Option("rebal-migrate-window", "size", default="4MB",
               description="copy window for file migration: the "
                           "migrator streams a file in windows of "
                           "this size instead of materializing it "
                           "whole (cluster.rebal-migrate-window)"),
    )

    #: reserved temp suffix for in-flight migration copies (hidden
    #: from listings like linkto files; the gateway reserves
    #: .gftpu.upload~ the same way)
    MIGRATE_SUFFIX = ".rebalance~"

    # throttle -> (concurrent migrations, cooperative sleep between
    # files).  The reference scales migrator THREADS (lazy=1,
    # normal=2, aggressive=max); the async analog bounds in-flight
    # migrations and, for lazy, yields the loop between files so
    # client fops interleave
    _THROTTLE = {"lazy": (1, 0.01), "normal": (2, 0.0),
                 "aggressive": (8, 0.0)}

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.n = len(self.children)
        if self.n < 1:
            raise ValueError(f"{self.name}: needs >= 1 child")
        # persisted-layout cache: dirpath -> (expiry, ranges) where
        # ranges = [(start, stop, child_idx)] or None (= derived split)
        self._layouts: dict[str, tuple[float, list | None]] = {}
        # live defrag status (gf_defrag_info analog), published by
        # rebalance() and polled by glusterd's drain for status ops
        self.rebal_status: dict = {"state": "not started"}
        self._recompute_active()

    def _recompute_active(self) -> None:
        gone = {s.strip() for s in
                self.opts["decommissioned"].split(",") if s.strip()}
        self._active = [i for i, c in enumerate(self.children)
                        if c.name not in gone]
        if not self._active:
            raise ValueError(f"{self.name}: every child decommissioned")
        # cached layouts (and their authoritative flags) were judged
        # against the OLD active set: a stale authoritative=True would
        # let lookup-optimize ENOENT files that moved routing
        self._layouts.clear()

    def reconfigure(self, options: dict) -> None:
        super().reconfigure(options)
        self._recompute_active()

    # -- placement ---------------------------------------------------------

    _RSYNC_RE = None  # compiled lazily; class-level cache

    def _munge_name(self, name: str) -> str:
        """cluster.rsync-hash-regex / extra-hash-regex: hash a rename
        pattern's capture so temp names hash where the final name will
        (dht_munge_name) — rsync's .NAME.XXXXXX otherwise lands on a
        random child and the final rename pays a migration."""
        import re

        for key in ("rsync-hash-regex", "extra-hash-regex"):
            spec = str(self.opts[key]).strip()
            if not spec or spec == "none":
                continue
            pat = r"^\.(.+)\.[^.]+$" if spec == "rsync" else spec
            try:
                m = re.match(pat, name)
            except re.error:
                continue
            if m and m.groups() and m.group(1):
                return m.group(1)
        return name

    def hashed_idx(self, name: str) -> int:
        """Even split of the 2^32 hash space over the ACTIVE children
        (dht_layout_t ranges; decommissioned nodes hold no range) —
        the DERIVED layout used when a directory has no persisted one."""
        span = (1 << 32) // len(self._active)
        return self._active[min(dm_hash(self._munge_name(name)) // span,
                                len(self._active) - 1)]

    def _hashed(self, loc: Loc) -> int:
        return self.hashed_idx(loc.name or loc.path.rsplit("/", 1)[-1])

    # -- persisted per-directory layouts (dht-layout.c / dht-selfheal.c) --

    @staticmethod
    def _parent_of(loc: Loc) -> str:
        p = loc.path.rstrip("/")
        return p.rsplit("/", 1)[0] or "/"

    def compute_ranges(self, weights: dict[str, float] | None = None,
                       seed: int = 0) -> list[tuple[int, int, int]]:
        """Split the 2^32 space over active children, proportionally to
        ``weights`` (by child NAME; missing = 1.0) — the weighted-layout
        capability derived layouts cannot express.

        cluster.subvols-per-directory: the split covers only that many
        children, rotated by ``seed`` (the directory path hash) so wide
        volumes spread directories without every dir spanning every
        child (dht_selfheal_layout_alloc spread-count)."""
        active = self._active
        spread = int(self.opts["subvols-per-directory"])
        if 0 < spread < len(active):
            start = seed % len(active)
            rot = active[start:] + active[:start]
            active = sorted(rot[:spread])
        ws = [max(0.0, float((weights or {}).get(
            self.children[i].name, 1.0))) for i in active]
        total = sum(ws) or float(len(active))
        ranges: list[tuple[int, int, int]] = []
        cursor = 0
        for pos, i in enumerate(active):
            stop = (1 << 32) - 1 if pos == len(active) - 1 else \
                cursor + max(1, int((1 << 32) * ws[pos] / total)) - 1
            stop = min(stop, (1 << 32) - 1)
            ranges.append((cursor, stop, i))
            cursor = stop + 1
            if cursor > (1 << 32) - 1:
                ranges.extend((0, -1, j) for j in active[pos + 1:])
                break
        return [r for r in ranges if r[1] >= r[0]]

    def _active_commit(self) -> int:
        """Layout generation for the CURRENT active child set (the
        vol_commit_hash analog): stored into every written layout, so a
        later child-set change makes old layouts non-authoritative."""
        return dm_hash("|".join(self.children[i].name
                                for i in self._active))

    async def _dir_meta(self, dirpath: str) -> tuple[list | None, bool]:
        """(persisted layout of ``dirpath`` or None, authoritative?).

        None layout = no child carries the xattr, or the union is
        anomalous (holes/overlap -> derived fallback; the reference
        treats those as needing a layout heal).  Authoritative = every
        record's commit matches the current child set, so a miss at the
        range owner proves absence (lookup-optimize)."""
        import time as _time

        hit = self._layouts.get(dirpath)
        now = _time.monotonic()
        if hit is not None and hit[0] > now:
            return hit[1], hit[2]
        loc = Loc(dirpath)
        ranges: list[tuple[int, int, int]] = []
        commits: set[int] = set()
        holders: list[int] = []
        found = False
        for i in range(self.n):
            try:
                out = await self.children[i].getxattr(loc, XA_LAYOUT)
            except FopError as e:
                if e.err not in (errno.ENOENT, errno.ESTALE):
                    # unreadable is not proof of absence (child down)
                    holders.append(i)
                continue
            holders.append(i)
            try:
                _v, commit, start, stop = struct.unpack(
                    _LAYOUT_FMT, out[XA_LAYOUT])
            except (KeyError, struct.error):
                continue
            found = True
            commits.add(commit)
            if stop >= start:
                ranges.append((start, stop, i))
        layout: list | None = None
        if found:
            ranges.sort()
            ok = bool(ranges) and ranges[0][0] == 0 and \
                ranges[-1][1] == (1 << 32) - 1 and \
                all(ranges[j][1] + 1 == ranges[j + 1][0]
                    for j in range(len(ranges) - 1))
            if ok:
                layout = ranges
            else:
                log.warning(2, "%s: anomalous layout on %s (%d ranges):"
                            " derived fallback", self.name, dirpath,
                            len(ranges))
        elif holders and len(holders) < self.n:
            # NO child carries a layout xattr and the directory exists
            # on a strict subset of children: a just-grown volume (the
            # pre-add-brick namespace had a single leg and no dht
            # records) before fix-layout reaches this directory.
            # Hashing over ALL children here would route new names at
            # a child with no parent to create them under; the
            # reference keeps such a directory on its existing subvols
            # until fix-layout stamps fresh ranges, so derive an even
            # split over the HOLDERS (never authoritative — a miss at
            # the derived owner proves nothing).
            span = (1 << 32) // len(holders)
            layout = [(j * span,
                       (1 << 32) - 1 if j == len(holders) - 1
                       else (j + 1) * span - 1, i)
                      for j, i in enumerate(holders)]
            commits.add(-1)
        authoritative = layout is not None and \
            commits == {self._active_commit()}
        self._layouts[dirpath] = (now + LAYOUT_TTL, layout, authoritative)
        if len(self._layouts) > 4096:  # bound: every entry re-derivable
            for k in list(self._layouts)[:2048]:
                self._layouts.pop(k, None)
        return layout, authoritative

    async def _dir_layout(self, dirpath: str) -> list | None:
        return (await self._dir_meta(dirpath))[0]

    async def _write_layout(self, dirpath: str,
                            ranges: list[tuple[int, int, int]]) -> None:
        """Persist one range per owning child on ITS copy of the dir;
        children that LOST their range (decommission + fix-layout) get
        the record removed, else the stale range overlaps the new union
        and every read degrades to the anomalous-layout fallback."""
        loc = Loc(dirpath)
        commit = self._active_commit()
        by_child = {idx: (start, stop) for start, stop, idx in ranges}
        for i in range(self.n):
            try:
                if i in by_child:
                    start, stop = by_child[i]
                    await self.children[i].setxattr(loc, {
                        XA_LAYOUT: struct.pack(_LAYOUT_FMT, 1, commit,
                                               start, stop)})
                else:
                    await self.children[i].removexattr(loc, XA_LAYOUT)
            except FopError as e:
                if e.err not in (errno.ENODATA, errno.ENOENT,
                                 errno.ESTALE):
                    log.warning(2, "%s: layout write on %s child %d: "
                                "%s", self.name, dirpath, i, e)
        import time as _time

        self._layouts[dirpath] = (_time.monotonic() + LAYOUT_TTL,
                                  sorted(ranges), True)

    async def _placed(self, loc: Loc) -> int:
        """Owning subvol for a basename per the parent's PERSISTED
        layout; derived split when none exists."""
        name = loc.name or loc.path.rsplit("/", 1)[-1]
        layout = await self._dir_layout(self._parent_of(loc))
        if layout:
            h = dm_hash(self._munge_name(name))
            for start, stop, idx in layout:
                if start <= h <= stop:
                    # a decommissioned child keeps its range until
                    # fix-layout; route around it like the derived path
                    return idx if idx in self._active else \
                        self.hashed_idx(name)
        return self.hashed_idx(name)

    async def fix_layout_dir(self, path: str,
                             weights: dict[str, float] | None = None
                             ) -> list[str]:
        """ONE directory's share of ``rebalance fix-layout``: create
        missing directory copies (a just-added brick has none),
        pre-place linktos for names the new ranges re-home, persist
        the new ranges.  No recursion — the rebalance daemon drives
        this per directory so its walk can CHECKPOINT between
        directories; returns the subdirectory names for the caller's
        descent.  Data stays put — only NEW names follow the new
        layout; the migration phase moves existing files."""
        loc = Loc(path)
        src = None
        for i in range(self.n):
            try:
                ia, _ = await self.children[i].lookup(loc)
                src = (i, ia)
                break
            except FopError:
                continue
        if src is None:
            raise FopError(errno.ENOENT, path)
        for i in self._active:
            if i == src[0]:
                continue
            try:
                await self.children[i].lookup(loc)
            except FopError:
                try:
                    await self.children[i].mkdir(
                        loc, src[1].mode & 0o7777,
                        {"gfid-req": src[1].gfid})
                except FopError:
                    pass
        ranges = self.compute_ranges(weights, seed=dm_hash(path))

        def owner_of(name: str) -> int:
            h = dm_hash(self._munge_name(name))
            for start, stop, idx in ranges:
                if start <= h <= stop:
                    return idx
            return self.hashed_idx(name)

        # walk under the OLD layout first: names the NEW ranges re-home
        # get a linkto at their new owner (dht_linkfile) BEFORE the new
        # layout goes live, so lookup-optimize's authoritative miss can
        # never lose a pre-fix file — its new position either holds the
        # file or points at it
        fd = await self.opendir(loc)
        try:
            entries = await self.readdirp(fd)
        finally:
            await self.release(fd)
        subdirs: list[str] = []
        for name, ia in entries:
            if ia is not None and ia.ia_type is IAType.DIR:
                subdirs.append(name)
                continue
            child = path.rstrip("/") + "/" + name
            cloc = Loc(child)
            try:
                cur = await self._cached_idx(cloc)
            except FopError:
                continue
            new_owner = owner_of(name)
            if new_owner != cur:
                try:
                    await self.children[new_owner].lookup(cloc)
                except FopError:
                    gfid = (await self.children[cur].lookup(cloc))[0].gfid
                    await self._make_linkto(new_owner, cloc, cur, gfid)
        await self._write_layout(path, ranges)
        return subdirs

    async def fix_layout(self, path: str = "/",
                         weights: dict[str, float] | None = None) -> dict:
        """Recompute + persist every directory's ranges over the CURRENT
        active children (``rebalance fix-layout``), recursively —
        the one-shot in-process form; the managed rebalance daemon
        runs the same per-directory step under its checkpointed walk."""
        if weights is None and self.opts["weighted-rebalance"]:
            weights = await self._capacity_weights()
        subdirs = await self.fix_layout_dir(path, weights)
        fixed = 1
        for name in subdirs:
            sub = await self.fix_layout(
                path.rstrip("/") + "/" + name, weights)
            fixed += sub["fixed"]
        return {"fixed": fixed, "path": path}

    async def _cached_idx(self, loc: Loc) -> int:
        """Subvol actually holding the file: hashed, linkto target, or
        global-lookup result (dht cached-subvol resolution)."""
        hi = await self._placed(loc)
        try:
            ia, _ = await self.children[hi].lookup(loc)
            if ia.ia_type is IAType.DIR:
                return hi
            link = await self._linkto(hi, loc)
            if link is not None:
                return link
            return hi
        except FopError as e:
            if e.err not in (errno.ENOENT, errno.ESTALE):
                raise
        if not self.opts["lookup-unhashed"]:
            raise FopError(errno.ENOENT, loc.path)
        if self.opts["lookup-optimize"] and loc.path:
            # an up-to-date persisted layout proves absence: every name
            # placed under it went to its range owner, and fix-layout
            # leaves linktos there for names the layout re-homed — the
            # fan-out would find nothing (cluster.lookup-optimize).
            # gfid-only locs (handle API) carry no name to place, so
            # they always take the everywhere pass.
            _, authoritative = await self._dir_meta(self._parent_of(loc))
            if authoritative:
                raise FopError(errno.ENOENT, loc.path)
        for i in range(self.n):  # everywhere-lookup
            if i == hi:
                continue
            try:
                await self.children[i].lookup(loc)
                return i
            except FopError:
                continue
        raise FopError(errno.ENOENT, loc.path)

    async def _locate_real(self, loc: Loc) -> tuple[int, "object"]:
        """(child index, iatt) of the REAL copy of ``loc`` — a direct
        scan of every child that ignores layout pruning and follows no
        pointers (linkto copies are skipped, not followed).  This is
        the MIGRATOR's resolution: a file created through a stale
        parent layout sits misplaced with no linkto, and the normal
        ``_cached_idx`` path would lookup-optimize it into ENOENT —
        unfindable is exactly what the rebalance walk exists to fix
        (dht_lookup_everywhere minus the pruning)."""
        for i in range(self.n):
            try:
                ia, _ = await self.children[i].lookup(loc)
            except FopError:
                continue
            if ia.ia_type is not IAType.DIR:
                try:
                    out = await self.children[i].getxattr(loc, XA_LINKTO)
                    if XA_LINKTO in out:
                        continue  # pointer, not content
                except FopError as e:
                    if e.err in (errno.ENOENT, errno.ESTALE):
                        continue  # vanished under the probe
                    if e.err != errno.ENODATA:
                        # unreadable is NOT proof of absence: calling
                        # a linkto "real" here would migrate its empty
                        # body as content, and a later pass would then
                        # take the committed-copy path and delete the
                        # actual data.  Propagate; the walk retries
                        # the file next pass
                        raise
            return i, ia
        raise FopError(errno.ENOENT, loc.path)

    async def _linkto(self, idx: int, loc: Loc) -> int | None:
        try:
            out = await self.children[idx].getxattr(loc, XA_LINKTO)
        except FopError:
            return None
        target = out[XA_LINKTO].decode()
        for i, c in enumerate(self.children):
            if c.name == target:
                return i
        return None

    # -- namespace fops ----------------------------------------------------

    async def _with_cached(self, loc: Loc, call):
        """Resolve + run with ONE re-resolution retry: a file being
        migrated can have its pointer torn down between our resolution
        and the fop (linkto followed to the source just as the
        migrator dropped it) — the reference heals this with
        lookup-everywhere on ESTALE (dht_lookup_everywhere); here the
        re-resolution finds the committed destination."""
        idx = await self._cached_idx(loc)
        try:
            return await call(idx)
        except FopError as e:
            if e.err not in (errno.ENOENT, errno.ESTALE):
                raise
            idx2 = await self._cached_idx(loc)
            if idx2 == idx:
                raise
            return await call(idx2)

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        return await self._with_cached(
            loc, lambda i: self.children[i].lookup(loc, xdata))

    async def stat(self, loc: Loc, xdata: dict | None = None):
        return await self._with_cached(
            loc, lambda i: self.children[i].stat(loc, xdata))

    async def lease(self, loc: Loc, cmd: str, ltype: str = "rd",
                    lease_id: str = "", xdata: dict | None = None):
        # leases must live where the writes land: route to the cached
        # subvol (the default first-child wind would park the lease on
        # a brick the hashed writer never touches, so conflicting
        # writes would never recall it)
        return await self._with_cached(
            loc, lambda i: self.children[i].lease(loc, cmd, ltype,
                                                  lease_id, xdata))

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        ctx: DhtFdCtx = fd.ctx_get(self)
        if ctx is None:
            return await self.stat(Loc(fd.path, gfid=fd.gfid), xdata)
        return await self.children[ctx.idx].fstat(ctx.child_fd, xdata)

    async def mkdir(self, loc: Loc, mode: int = 0o755,
                    xdata: dict | None = None):
        self._check_reserved(loc)
        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        results = []
        errs = []
        for i in range(self.n):  # directories live everywhere
            try:
                results.append(await self.children[i].mkdir(loc, mode, xdata))
            except FopError as e:
                errs.append(e)
        if not results:
            raise errs[0]
        # persist the new directory's hash ranges (dht_selfheal_dir:
        # every fresh dir gets a layout written at creation)
        await self._write_layout(loc.path,
                                 self.compute_ranges(
                                     seed=dm_hash(loc.path)))
        return results[0]

    async def rmdir(self, loc: Loc, flags: int = 0,
                    xdata: dict | None = None):
        last = None
        ok = 0
        for i in range(self.n):
            try:
                await self.children[i].rmdir(loc, flags, xdata)
                ok += 1
            except FopError as e:
                if e.err != errno.ENOENT:
                    last = e
        if ok == 0 and last:
            raise last
        return {}

    async def _sched(self, loc: Loc) -> int:
        """Which subvol NEW files land on: the parent's persisted
        layout, DIVERTED when that child is over the free-space or
        free-inode floor (dht_is_subvol_filled / dht_free_disk_
        available_subvol: the create lands on the roomiest child and
        the hashed position gets a linkto).  The nufa/switch variants
        override this with their policy placement (dht_methods)."""
        idx = await self._placed(loc)
        if await self._subvol_filled(idx):
            best, best_free = None, -1.0
            for i in self._active:
                if i == idx or await self._subvol_filled(i):
                    continue
                free = (self._du.get(i) or (0, 0.0, 0.0))[1]
                if free > best_free:
                    best, best_free = i, free
            if best is not None:
                return best
        return idx

    _DU_TTL = 5.0  # seconds a child's statfs sample stays trusted

    async def _subvol_filled(self, i: int) -> bool:
        """Cached per-child statfs vs cluster.min-free-disk/-inodes."""
        du = getattr(self, "_du", None)
        if du is None:
            du = self._du = {}
        ent = du.get(i)
        now = time.monotonic()
        if ent is None or now - ent[0] > self._DU_TTL:
            try:
                sv = await self.children[i].statfs(Loc("/"))
                blocks = max(1, sv.get("blocks", 1))
                files = max(1, sv.get("files", 1) or 1)
                ent = (now, sv.get("bavail", blocks) / blocks * 100.0,
                       sv.get("ffree", files) / files * 100.0)
            except (FopError, AttributeError):
                ent = (now, 100.0, 100.0)  # unknowable: don't divert
            du[i] = ent
        return ent[1] < float(self.opts["min-free-disk"]) or \
            ent[2] < float(self.opts["min-free-inodes"])

    async def _capacity_weights(self) -> dict[str, float]:
        """cluster.weighted-rebalance: child capacity shares for
        fix-layout range sizing (dht_get_du_info)."""
        out: dict[str, float] = {}
        for i in self._active:
            try:
                sv = await self.children[i].statfs(Loc("/"))
                out[self.children[i].name] = float(
                    max(1, sv.get("blocks", 1)))
            except (FopError, AttributeError):
                out[self.children[i].name] = 1.0
        total = sum(out.values())
        return {k: v / total * len(out) for k, v in out.items()}

    def _check_reserved(self, loc: Loc) -> None:
        """Refuse user names carrying the reserved migration suffix:
        such a name would be hidden from every listing (the temp
        filter) and then unconditionally reclaimed by the rebalance
        orphan sweep — accepted, it silently hides and later silently
        DELETES user data.  The migrator itself never enters through
        this layer (it drives the children directly)."""
        name = loc.path.rstrip("/").rpartition("/")[2]
        if name.endswith(self.MIGRATE_SUFFIX):
            raise FopError(
                errno.EPERM,
                f"{loc.path}: the {self.MIGRATE_SUFFIX!r} suffix is "
                "reserved for migration temps")

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        self._check_reserved(loc)
        if flags & os.O_EXCL:
            # O_EXCL must see the file ANYWHERE: the scheduler may
            # target a subvol other than the holder (nufa/switch local
            # placement, layout drift), and creating there would FORK
            # the file — two data copies, the old one orphaned.
            # Resolution costs one child probe under an authoritative
            # layout (linktos stand in for re-homed names).
            try:
                await self._cached_idx(loc)
            except FopError as e:
                if e.err not in (errno.ENOENT, errno.ESTALE):
                    raise
            else:
                raise FopError(errno.EEXIST, loc.path)
        idx = await self._sched(loc)
        fd_c, ia = await self.children[idx].create(loc, flags, mode, xdata)
        hi = await self._placed(loc)
        if hi != idx:
            # scheduled off the hashed subvol: leave the lookup pointer
            # (dht_linkfile_create in nufa_create_cbk / switch)
            await self._make_linkto(hi, loc, idx, ia.gfid)
        fd = FdObj(ia.gfid, flags, path=loc.path)
        fd.ctx_set(self, DhtFdCtx(idx, fd_c))
        return fd, ia

    async def open(self, loc: Loc, flags: int = 0, xdata: dict | None = None):
        fds: dict = {}

        async def one(i):
            fds["idx"] = i
            return await self.children[i].open(loc, flags, xdata)

        fd_c = await self._with_cached(loc, one)
        fd = FdObj(fd_c.gfid, flags, path=loc.path)
        fd.ctx_set(self, DhtFdCtx(fds["idx"], fd_c))
        return fd

    async def mknod(self, loc: Loc, mode: int = 0o644, rdev: int = 0,
                    xdata: dict | None = None):
        self._check_reserved(loc)
        idx = await self._sched(loc)
        ia = await self.children[idx].mknod(loc, mode, rdev, xdata)
        hi = await self._placed(loc)
        if hi != idx:
            await self._make_linkto(hi, loc, idx, ia.gfid)
        return ia

    async def symlink(self, target: str, loc: Loc, xdata: dict | None = None):
        self._check_reserved(loc)
        return await self.children[await self._placed(loc)].symlink(
            target, loc, xdata)

    async def readlink(self, loc: Loc, xdata: dict | None = None):
        idx = await self._cached_idx(loc)
        return await self.children[idx].readlink(loc, xdata)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        idx = await self._cached_idx(loc)
        hi = await self._placed(loc)
        if idx != hi:  # drop the linkto too
            try:
                await self.children[hi].unlink(loc, xdata)
            except FopError:
                pass
        return await self.children[idx].unlink(loc, xdata)

    async def link(self, oldloc: Loc, newloc: Loc, xdata: dict | None = None):
        self._check_reserved(newloc)
        idx = await self._cached_idx(oldloc)
        return await self.children[idx].link(oldloc, newloc, xdata)

    async def rename(self, oldloc: Loc, newloc: Loc,
                     xdata: dict | None = None):
        self._check_reserved(newloc)
        src = await self._cached_idx(oldloc)
        ia, _ = await self.children[src].lookup(oldloc)
        if ia.ia_type is IAType.DIR:  # dirs: rename everywhere
            out = None
            for i in range(self.n):
                try:
                    out = await self.children[i].rename(oldloc, newloc, xdata)
                except FopError:
                    pass
            if out is None:
                raise FopError(errno.EIO, "dir rename failed everywhere")
            return out
        dst_hashed = await self._placed(newloc)
        # POSIX rename overwrites an existing destination.  The rename on
        # src only replaces a same-subvol dst; a live dst file elsewhere
        # must be unlinked, or _make_linkto would silently convert it into
        # a pointer and orphan its data (reference dht_rename unlinks the
        # dst cached file).  Resolve dst BEFORE the rename (afterwards the
        # lookup would find the renamed file) but unlink only AFTER it
        # succeeds — a failed rename must leave dst intact.
        try:
            dst_cached = await self._cached_idx(newloc)
        except FopError:
            dst_cached = None
        out = await self.children[src].rename(oldloc, newloc, xdata)
        for i in {dst_cached, dst_hashed} - {None, src}:
            try:
                await self.children[i].unlink(newloc)
            except FopError:
                pass
        if dst_hashed != src:
            # data stayed on src subvol: leave a linkto pointer at the
            # dst-hashed subvol (dht-linkfile.c:95)
            await self._make_linkto(dst_hashed, newloc, src, ia.gfid)
        # stale linkto at old hashed location?
        old_hashed = await self._placed(oldloc)
        if old_hashed != src:
            try:
                await self.children[old_hashed].unlink(oldloc)
            except FopError:
                pass
        return out

    async def _make_linkto(self, idx: int, loc: Loc, target: int,
                           gfid: bytes) -> None:
        try:
            await self.children[idx].mknod(loc, 0o1000, 0,
                                           {"gfid-req": gfid})
        except FopError as e:
            if e.err != errno.EEXIST:
                raise
        await self.children[idx].setxattr(
            loc, {XA_LINKTO: self.children[target].name.encode()})

    # -- data fops (forward to cached subvol) ------------------------------

    async def _fd_target(self, fd: FdObj) -> tuple[int, FdObj]:
        ctx: DhtFdCtx | None = fd.ctx_get(self)
        if ctx is not None:
            return ctx.idx, ctx.child_fd
        # fd from a retired graph (hot graph swap) or anonymous: resolve
        # the cached subvol again and address by gfid (the reference
        # migrates fds onto the new graph; anonymous fds carry it here)
        if not fd.path and not fd.gfid:
            raise FopError(errno.EBADF, "dht: unknown fd")
        idx = await self._cached_idx(Loc(fd.path, gfid=fd.gfid))
        cfd = FdObj(fd.gfid, fd.flags, path=fd.path, anonymous=True)
        fd.ctx_set(self, DhtFdCtx(idx, cfd))
        return idx, cfd

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        i, cfd = await self._fd_target(fd)
        return await self.children[i].readv(cfd, size, offset, xdata)

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        i, cfd = await self._fd_target(fd)
        return await self.children[i].writev(cfd, data, offset, xdata)

    async def xorv(self, fd: FdObj, data, offset: int,
                   xdata: dict | None = None):
        # routed like writev (fd-addressed data fop): the base-class
        # first-child default would land the delta on the wrong subvol
        i, cfd = await self._fd_target(fd)
        return await self.children[i].xorv(cfd, data, offset, xdata)

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        i, cfd = await self._fd_target(fd)
        return await self.children[i].flush(cfd, xdata)

    async def fsync(self, fd: FdObj, datasync: int = 0,
                    xdata: dict | None = None):
        i, cfd = await self._fd_target(fd)
        return await self.children[i].fsync(cfd, datasync, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        i, cfd = await self._fd_target(fd)
        return await self.children[i].ftruncate(cfd, size, xdata)

    async def fallocate(self, fd: FdObj, mode: int, offset: int,
                        length: int, xdata: dict | None = None):
        i, cfd = await self._fd_target(fd)
        return await self.children[i].fallocate(cfd, mode, offset, length,
                                                xdata)

    async def discard(self, fd: FdObj, offset: int, length: int,
                      xdata: dict | None = None):
        i, cfd = await self._fd_target(fd)
        return await self.children[i].discard(cfd, offset, length, xdata)

    async def zerofill(self, fd: FdObj, offset: int, length: int,
                       xdata: dict | None = None):
        i, cfd = await self._fd_target(fd)
        return await self.children[i].zerofill(cfd, offset, length, xdata)

    async def seek(self, fd: FdObj, offset: int, what: str = "data",
                   xdata: dict | None = None):
        i, cfd = await self._fd_target(fd)
        return await self.children[i].seek(cfd, offset, what, xdata)

    async def release(self, fd: FdObj):
        ctx = fd.ctx_del(self)
        if isinstance(ctx, dict):
            # directory fd (opendir fans out): one child fd per subvol
            for i, cfd in ctx.items():
                rel = getattr(self.children[i], "release", None)
                if rel:
                    await rel(cfd)
        elif ctx:
            rel = getattr(self.children[ctx.idx], "release", None)
            if rel:
                await rel(ctx.child_fd)

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        idx = await self._cached_idx(loc)
        return await self.children[idx].truncate(loc, size, xdata)

    async def setattr(self, loc: Loc, attrs: dict, valid: int = 0,
                      xdata: dict | None = None):
        idx = await self._cached_idx(loc)
        ia, _ = await self.children[idx].lookup(loc)
        if ia.ia_type is IAType.DIR:
            out = None
            for i in range(self.n):
                try:
                    out = await self.children[i].setattr(loc, attrs, valid,
                                                         xdata)
                except FopError:
                    pass
            return out
        return await self.children[idx].setattr(loc, attrs, valid, xdata)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        idx = await self._cached_idx(loc)
        return await self.children[idx].setxattr(loc, xattrs, flags, xdata)

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        idx = await self._cached_idx(loc)
        out = await self.children[idx].getxattr(loc, name, xdata)
        if name is None:
            out.pop(XA_LINKTO, None)
        return out

    async def removexattr(self, loc: Loc, name: str,
                          xdata: dict | None = None):
        idx = await self._cached_idx(loc)
        return await self.children[idx].removexattr(loc, name, xdata)

    async def statfs(self, loc: Loc, xdata: dict | None = None):
        """Aggregate capacity across subvols (dht sums them)."""
        out = None
        for i in range(self.n):
            try:
                sv = await self.children[i].statfs(loc, xdata)
            except FopError:
                continue
            if out is None:
                out = dict(sv)
            else:
                for k in ("blocks", "bfree", "bavail", "files", "ffree"):
                    out[k] += sv[k]
        if out is None:
            raise FopError(errno.ENOTCONN, "no children for statfs")
        return out

    # -- directory reads: merge all subvols --------------------------------

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        fds = {}
        gfid = None
        for i in range(self.n):
            try:
                cfd = await self.children[i].opendir(loc, xdata)
                fds[i] = cfd
                gfid = gfid or cfd.gfid
            except FopError:
                continue
        if not fds:
            raise FopError(errno.ENOENT, loc.path)
        fd = FdObj(gfid, path=loc.path)
        fd.ctx_set(self, fds)
        return fd

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        fds: dict = fd.ctx_get(self) or {}
        seen: set[str] = set()
        out = []
        rd_opt = self.opts["readdir-optimize"]
        first_up = None  # first child that actually ANSWERS readdir
        for i, cfd in fds.items():
            try:
                entries = await self.children[i].readdir(cfd, size, 0, xdata)
            except FopError:
                continue
            if first_up is None:
                first_up = i
            for name, ia in entries:
                if name in seen:
                    continue
                if name.endswith(self.MIGRATE_SUFFIX):
                    # in-flight (or crash-orphaned) migration copy:
                    # reserved namespace, never listed — like the
                    # linkto pointers below
                    continue
                if rd_opt and i != first_up and ia is not None and \
                        ia.ia_type is IAType.DIR:
                    # cluster.readdir-optimize: directories exist on
                    # every child — list them from the first one only
                    # (dht_readdirp_cbk; same caveat as the reference:
                    # a dir copy pending heal there goes unlisted)
                    continue
                # hide linkto pointer files
                if await self._is_linkto(i, fd.path, name):
                    continue
                seen.add(name)
                out.append((name, ia))
        out.sort(key=lambda e: e[0])
        return out[offset:]

    async def _is_linkto(self, idx: int, dirpath: str, name: str) -> bool:
        child = dirpath.rstrip("/") + "/" + name
        try:
            await self.children[idx].getxattr(Loc(child), XA_LINKTO)
            return True
        except FopError:
            return False

    async def readdirp(self, fd: FdObj, size: int = 0, offset: int = 0,
                       xdata: dict | None = None):
        entries = await self.readdir(fd, size, offset, xdata)
        out = []
        for name, ia in entries:
            if ia is None:
                try:
                    ia = await self.stat(
                        Loc(fd.path.rstrip("/") + "/" + name))
                except FopError:
                    pass
            out.append((name, ia))
        return out

    # -- rebalance (dht-rebalance.c dht_migrate_file) ----------------------

    #: xattr namespaces that are a CHILD's private metadata, never
    #: copied across subvolumes by migration (EC fragment counters
    #: describe the source group's fragments; dht layout/linkto
    #: records are position, not content)
    _MIGRATE_XATTR_SKIP = ("trusted.ec.", "trusted.glusterfs.",
                           "trusted.bit-rot", "glusterfs.")

    async def _migrate_file(self, cloc: Loc, ia, idx: int,
                            hi: int) -> int:
        """Move one file idx -> hi (dht_migrate_file analog), torn-read
        safe: the bytes land in a reserved-suffix temp on the
        destination child — hidden from listings, never a resolution
        target, copied as ONE compound chain per window where the
        graph carries it — get fsynced, and a same-child RENAME
        commits them over the destination name atomically.  A
        concurrent reader therefore sees the old full file (via the
        existing linkto / global lookup to the source) or the new full
        file, never a partial copy.  The source must be QUIESCENT: its
        iatt is re-checked against the pre-copy snapshot and a changed
        source re-copies (bounded — the reference's
        migration-in-progress phase-2 check).  Cleanup unlinks carry
        the internal-op xdata flag so features/trash never captures
        migration garbage (trash.c internal_op).  Returns bytes
        moved."""
        from ..features.trash import INTERNAL_OP

        internal = {INTERNAL_OP: True}
        window = max(64 * 1024, int(self.opts["rebal-migrate-window"]))
        src, dst = self.children[idx], self.children[hi]
        dirpath, _, name = cloc.path.rstrip("/").rpartition("/")
        tmp = Loc(f"{dirpath}/.{name}{self.MIGRATE_SUFFIX}")
        # a migrator that died between its rename commit and the source
        # unlink left TWO real copies.  The rename commit is the only
        # way a pointer-free file lands at the hashed child, so a real
        # copy standing there IS the committed one — and clients have
        # been resolving to it ever since (hashed wins _cached_idx),
        # possibly writing.  Re-copying the stale source over it would
        # silently revert those writes: finish the dead migrator's
        # teardown instead.  Only definite absence answers may steer
        # back to the copy path — a transport error (ENOTCONN under
        # the failfast plane) proves nothing, and guessing either way
        # risks deleting the only real copy or clobbering the
        # committed one; propagate, count failed, retry later.
        committed = False
        try:
            await dst.lookup(cloc)
        except FopError as e:
            if e.err not in (errno.ENOENT, errno.ESTALE):
                raise
        else:
            try:
                await dst.getxattr(cloc, XA_LINKTO)
                # marker standing: a pointer, not a committed copy —
                # clients are still routed to the source; migrate
            except FopError as e:
                if e.err not in (errno.ENODATA, errno.ENOENT,
                                 errno.ESTALE):
                    raise
                committed = True
        if committed:
            # a failed teardown unlink propagates too: falling through
            # would re-copy the stale source over the committed copy
            await src.unlink(cloc, dict(internal))
            return 0
        moved = -1
        try:
            for _attempt in range(5):
                # a crash-orphaned temp (or a failed previous attempt)
                # would EEXIST the O_EXCL create
                try:
                    await dst.unlink(tmp, dict(internal))
                except FopError:
                    pass
                moved = await self._migrate_copy(src, dst, cloc, tmp,
                                                 ia, window, internal)
                if moved < 0:  # source moved under the copy: go again
                    ia, _ = await src.lookup(cloc)
                    continue
                # final pre-commit re-check: narrows the lost-write
                # race from the whole copy duration to lookup->rename.
                # (The residual window is real — the reference closes
                # it with its locked phase-2 delta sync; documented in
                # docs/rebalance.md failure semantics.)
                # a failed re-check ABORTS (cleanup below reclaims
                # the temp): a gone source means a serving client
                # unlinked or renamed the file away after our copy —
                # committing it would RESURRECT deleted data — and an
                # unreachable source can't prove quiescence either
                # way; a later pass re-decides against live state
                ia3, _ = await src.lookup(cloc)
                if ia3 is not None and \
                        (ia3.size, ia3.mtime) != (ia.size, ia.mtime):
                    ia = ia3
                    moved = -1
                    continue
                break
            if moved < 0:
                raise FopError(errno.EBUSY,
                               f"{cloc.path}: source never quiesced")
            # commit: one atomic same-child swap over the destination
            # name (and over the stale linkto standing there)
            await dst.rename(tmp, cloc)
        except BaseException:
            # ANY exit before the rename commit reclaims the hidden
            # temp: the suffix is filtered from every listing, so an
            # escape here (source unlinked mid-retry, rename failure,
            # never-quiesced give-up) would leak up to the whole
            # file's bytes invisibly until a post-crash RESUMED walk
            # happened to sweep this directory
            try:
                await dst.unlink(tmp, dict(internal))
            except (FopError, asyncio.CancelledError):
                pass
            raise
        # the replaced linkto shared the file's gfid, and brick xattr
        # stores are gfid-keyed: drop the pointer marker or the
        # committed file keeps routing readers at the source.  Only a
        # marker-already-absent answer may pass — any other failure
        # must abort BEFORE the source unlink below, or readers follow
        # the surviving marker to a deleted source forever; failing
        # here leaves the file served from the source and a later
        # pass retries the whole migration
        try:
            await dst.removexattr(cloc, XA_LINKTO)
        except FopError as e:
            if e.err not in (errno.ENODATA, errno.ENOENT,
                             errno.ESTALE):
                raise
        # drop the source copy; readers that raced the teardown
        # re-resolve through _with_cached to the committed destination
        await src.unlink(cloc, dict(internal))
        return moved

    @staticmethod
    def _delta_stripe(dst) -> int:
        """Stripe width of ``dst`` when a streamed migration copy can
        ride its parity-delta write plane, else 0.  Mirrors the gates
        of ec._delta_eligible that are knowable up front: a healthy
        systematic disperse group with delta-writes on and no brick
        having refused xorv.  Anything else (protocol/client, afr, a
        degraded group) keeps today's byte-identical streaming."""
        opts = getattr(dst, "opts", None)
        if (getattr(dst, "type_name", "") != "cluster/disperse"
                or not opts
                or not opts.get("systematic")
                or not opts.get("delta-writes")
                or not getattr(dst, "_xorv_ok", False)):
            return 0
        up = getattr(dst, "up", None)
        if not up or not all(up):
            return 0
        return int(getattr(dst, "stripe", 0))

    async def _migrate_copy(self, src, dst, cloc: Loc, tmp: Loc, ia,
                            window: int, internal: dict) -> int:
        """One copy attempt of ``cloc`` into the hidden temp on
        ``dst``.  Returns bytes copied, or -1 when the source changed
        under the copy (caller re-snapshots and retries).  Memory is
        bounded by ``window``: a file at or under it rides ONE
        compound chain (the smallfile common case — create + writev +
        setxattr + fsync + release in one frame where the graph
        carries it); a larger file streams window-at-a-time through a
        plain fd so a multi-GB migration never materializes the file
        (the option's contract).  The temp carries the file's OWN
        gfid (like the seed's direct create): clients cache
        path->gfid dentries, and a re-minted gfid would ESTALE every
        cached handle after the commit.  Destination is fsynced
        BEFORE the swap (the rebalance.ensure-durability contract): a
        crash right after the rename must not leave the only copy in
        page cache.  A failed copy unlinks its partial temp.

        On a delta-ready systematic disperse destination the streaming
        path is stripe-aware (ROADMAP item 3, narrow form): the window
        is rounded down to a stripe multiple so every full window is a
        pure encode (no RMW read at all), and the temp is pre-sized
        with ftruncate so the unaligned tail write lands strictly
        inside the true size — exactly the shape `_delta_eligible`
        routes onto the PR-10 parity-delta path instead of a full
        read-modify-write of the final stripe."""
        from ..rpc import compound as cfop

        size = ia.size
        chunks: list[bytes] = []
        sfd = await src.open(cloc, os.O_RDONLY)
        dfd = None
        try:
            if size <= window:
                off = 0
                while off < size:
                    data = await src.readv(sfd, size - off, off)
                    b = bytes(data)
                    if not b:
                        break
                    chunks.append(b)
                    off += len(b)
            else:
                stripe = self._delta_stripe(dst)
                if stripe and window >= stripe:
                    window = window // stripe * stripe
                dfd, _ = await dst.create(
                    tmp, os.O_RDWR | os.O_EXCL, ia.mode & 0o7777,
                    {"gfid-req": ia.gfid})
                if stripe:
                    await dst.ftruncate(dfd, size)
                off = 0
                while off < size:
                    data = await src.readv(sfd, min(window, size - off),
                                           off)
                    b = bytes(data)
                    if not b:
                        break
                    await dst.writev(dfd, b, off)
                    off += len(b)
            xattrs = await src.getxattr(cloc)
            ia2, _ = await src.lookup(cloc)
            if (ia2.size, ia2.mtime) != (size, ia.mtime):
                return -1
            clean = {k: v for k, v in xattrs.items()
                     if not k.startswith(self._MIGRATE_XATTR_SKIP)}
            if dfd is None:
                links: list = [("create",
                                (tmp, os.O_RDWR | os.O_EXCL,
                                 ia.mode & 0o7777,
                                 {"gfid-req": ia.gfid}),
                                {})]
                w = 0
                for b in chunks:
                    links.append(("writev", (cfop.FdRef(0), b, w), {}))
                    w += len(b)
                if clean:
                    links.append(("setxattr", (tmp, clean), {}))
                links.append(("fsync", (cfop.FdRef(0), 0), {}))
                links.append(("release", (cfop.FdRef(0),), {}))
                replies = await dst.compound(links)
                err = cfop.first_error(replies)
                if err is not None:
                    raise err
                return w
            if clean:
                await dst.setxattr(tmp, clean)
            await dst.fsync(dfd, 0)
            return off
        except FopError:
            try:
                await dst.unlink(tmp, dict(internal))
            except FopError:
                pass
            raise
        finally:
            rel = getattr(src, "release", None)
            if rel:
                await rel(sfd)
            if dfd is not None:
                await dst.release(dfd)

    async def rebalance(self, path: str = "/") -> dict:
        """Move every misplaced file to its hashed subvolume.

        Migrations run ``cluster.rebal-throttle`` wide (dht-rebalance.c
        gf_defrag_start_crawl thread scaling: lazy yields to client
        I/O, aggressive saturates); the throttle option is read per
        wave, so ``volume set`` retunes a RUNNING rebalance.  Live
        progress is published in ``self.rebal_status`` (the defrag
        status the reference reports via glusterd)."""
        st = self.rebal_status = {
            "state": "running", "throttle": self.opts["rebal-throttle"],
            "scanned": 0, "moved": 0, "failed": 0, "skipped": 0,
            "bytes_moved": 0, "started": time.time(), "elapsed": 0.0,
            "max_inflight": 0,
        }
        moved: list[tuple] = []

        from ..mgmt.svcutil import ThrottleWave

        async def walk_dir(path: str) -> None:
            fd = await self.opendir(Loc(path))
            try:
                entries = await self.readdir(fd)
            finally:
                await self.release(fd)
            wave = ThrottleWave()

            async def migrate(child: str, cloc: Loc, ia, idx: int,
                              hi: int) -> None:
                t0 = time.monotonic()
                try:
                    nbytes = await self._migrate_file(cloc, ia, idx, hi)
                except Exception as e:
                    # ANY escape counts as failed — tasks collected via
                    # asyncio.wait never re-raise, so an uncounted
                    # exception would report a clean 'completed' run
                    # with the file still misplaced
                    st["failed"] += 1
                    log.warning(22, "migrate %s failed: %r", child, e)
                    return
                if self.opts["rebalance-stats"]:
                    # cluster.rebalance-stats: per-file timing on the
                    # live defrag status (gf_defrag status run-time)
                    files = st.setdefault("file_times", [])
                    files.append({"path": child,
                                  "secs": round(time.monotonic() - t0,
                                                4),
                                  "bytes": nbytes})
                    del files[:-50]  # bound the live status payload
                moved.append((child, idx, hi))
                st["moved"] += 1
                st["bytes_moved"] += nbytes

            for name, _ in entries:
                child = path.rstrip("/") + "/" + name
                cloc = Loc(child)
                idx = await self._cached_idx(cloc)
                ia, _ = await self.children[idx].lookup(cloc)
                if ia.ia_type is IAType.DIR:
                    await walk_dir(child)
                    continue
                st["scanned"] += 1
                hi = await self._placed(cloc)
                if hi == idx:
                    st["skipped"] += 1
                    continue
                width, pause = self._THROTTLE[
                    self.opts["rebal-throttle"]]
                st["throttle"] = self.opts["rebal-throttle"]
                await wave.admit(migrate(child, cloc, ia, idx, hi),
                                 width, pause)
                st["max_inflight"] = max(st["max_inflight"],
                                         wave.max_inflight)
            await wave.drain()

        try:
            await walk_dir(path)
            st["state"] = "completed"
        except BaseException:
            st["state"] = "failed"
            raise
        finally:
            st["elapsed"] = round(time.time() - st["started"], 3)
        return {"moved": moved, "scanned": st["scanned"],
                "status": dict(st)}

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Single-subvolume fast path: on a one-brick distribute volume
        a self-contained chain (every fd it creates is released by a
        later link of the same chain) forwards intact — there is no
        alternative placement, no linkto bookkeeping, and no dht fd
        context can leak.  Everything else decomposes through the
        normal routed fops."""
        from ..rpc import compound as cfop

        if len(self.children) == 1 and len(self._active) == 1:
            produced = set()
            released = set()
            for i, (fop, args, _kw) in enumerate(links):
                if fop in cfop.FD_PRODUCERS:
                    produced.add(i)
                elif fop == "release" and args and \
                        isinstance(args[0], cfop.FdRef):
                    released.add(args[0].index)
            if produced <= released:
                # translate caller-owned fds to the CHILD fd (the
                # per-fop _fd_target step) — forwarding the dht-level
                # FdObj would silently degrade every fused write to an
                # anonymous gfid-addressed fd re-opened per op
                fwd = []
                for fop, args, kwargs in links:
                    nargs = []
                    for a in args:
                        if isinstance(a, FdObj):
                            _idx, a = await self._fd_target(a)
                        nargs.append(a)
                    nkw = {}
                    for k, v in kwargs.items():
                        if isinstance(v, FdObj):
                            _idx, v = await self._fd_target(v)
                        nkw[k] = v
                    fwd.append((fop, tuple(nargs), nkw))
                return await self.children[0].compound(fwd, xdata)
        return await cfop.decompose(self, links, xdata)

    def dump_private(self) -> dict:
        span = (1 << 32) // len(self._active)
        ranges = {idx: [j * span, (j + 1) * span - 1]
                  for j, idx in enumerate(self._active)}
        return {"subvolumes": self.n,
                "layout": [{"subvol": c.name,
                            "range": ranges.get(i, "decommissioned")}
                           for i, c in enumerate(self.children)]}
