"""cluster/replicate — synchronous N-way replication (AFR).

Reference: xlators/cluster/afr (30k LoC).  Behaviors kept:

* **Transactions** (afr-transaction.c:1087,629): pre-op mark dirty, wind
  the write to every up child, post-op bump the committed version on the
  children that succeeded — divergence marks heal candidates.  The
  reference's per-peer pending-xattr matrix collapses to per-brick
  (version, dirty) counters, which identify staleness the same way the
  EC layer's do (shared transaction skeleton, SURVEY.md §7 phase 3).
* **Quorum** (afr quorum-type auto): writes need a majority (or the
  configured ``quorum-count``); reads need one up-to-date child.
* **Read transactions** (afr-read-txn.c:94-229): reads pick one
  consistent child per ``read-hash-mode`` and fail over to another on
  error.
* **Self-heal** (afr-self-heal-data.c): full-file copy from a good child
  to stale ones under lock, then counter realignment; entry heal
  reconciles directory listings.

Xattr schema per brick: ``trusted.afr.version`` (2 u64: data, metadata),
``trusted.afr.dirty`` (2 u64) — same codec as the EC layer.
"""

from __future__ import annotations

import asyncio
import errno
import struct
from collections import Counter

from ..core.fops import FopError
from ..core.iatt import IAType, Iatt, gfid_new
from ..core.layer import Event, FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("afr")

XA_VERSION = "trusted.afr.version"
XA_DIRTY = "trusted.afr.dirty"


def _u64x2(data: bytes | None) -> tuple[int, int]:
    if not data:
        return (0, 0)
    return struct.unpack(">QQ", data.ljust(16, b"\0")[:16])


def _pack_u64x2(a: int, b: int) -> bytes:
    return struct.pack(">QQ", a, b)


class AfrFdCtx:
    __slots__ = ("child_fds", "flags")

    def __init__(self, child_fds: dict[int, FdObj], flags: int):
        self.child_fds = child_fds
        self.flags = flags


@register("cluster/replicate")
class ReplicateLayer(Layer):
    OPTIONS = (
        Option("quorum-count", "int", default=0, min=0,
               description="0 = auto (majority)"),
        Option("read-hash-mode", "enum", default="gfid-hash",
               values=("first-up", "gfid-hash", "round-robin")),
        Option("self-heal-window-size", "size", default="1M"),
        Option("favorite-child", "int", default=-1, min=-1,
               description="split-brain resolution source (-1 = none)"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.n = len(self.children)
        if self.n < 2:
            raise ValueError(f"{self.name}: replicate needs >= 2 children")
        self.up = [True] * self.n
        self._locks: dict[bytes, asyncio.Lock] = {}
        self._rr = 0
        self._lk_owner = gfid_new()
        self._locks_supported: bool | None = None

    # -- membership --------------------------------------------------------

    def notify(self, event: Event, source=None, data=None):
        if event is Event.UPCALL:
            for p in self.parents:
                p.notify(event, self, data)
            return
        if source in self.children:
            idx = self.children.index(source)
            if event is Event.CHILD_DOWN:
                self.up[idx] = False
            elif event is Event.CHILD_UP:
                self.up[idx] = True
            ev = Event.CHILD_UP if sum(self.up) >= self._quorum() else \
                Event.CHILD_DOWN
            for p in self.parents:
                p.notify(ev, self, data)
            return
        super().notify(event, source, data)

    def set_child_up(self, idx: int, up: bool) -> None:
        self.up[idx] = up

    def _up_idx(self) -> list[int]:
        return [i for i, u in enumerate(self.up) if u]

    def _quorum(self) -> int:
        q = self.opts["quorum-count"]
        return q if q else self.n // 2 + 1

    def _lock(self, key: bytes) -> asyncio.Lock:
        lk = self._locks.get(key)
        if lk is None:
            lk = self._locks[key] = asyncio.Lock()
        return lk

    # -- dispatch / combine ------------------------------------------------

    async def _dispatch(self, idxs, op: str, argfn):
        async def one(i):
            args, kwargs = argfn(i)
            return await getattr(self.children[i], op)(*args, **kwargs)

        results = await asyncio.gather(*(one(i) for i in idxs),
                                       return_exceptions=True)
        return dict(zip(idxs, results))

    def _combine(self, res: dict, min_ok: int | None = None):
        min_ok = self._quorum() if min_ok is None else min_ok
        good = {i: r for i, r in res.items()
                if not isinstance(r, BaseException)}
        if len(good) >= min_ok:
            return good
        errs = [r.err for r in res.values() if isinstance(r, FopError)]
        if errs:
            raise FopError(Counter(errs).most_common(1)[0][0],
                           f"{len(good)}/{len(res)} children succeeded")
        for r in res.values():
            if isinstance(r, BaseException):
                raise r
        raise FopError(errno.EIO, "quorum failure")

    async def _get_meta(self, idxs, loc: Loc):
        res = await self._dispatch(idxs, "getxattr",
                                   lambda i: ((loc, None), {}))
        out = {}
        for i, r in res.items():
            if isinstance(r, BaseException):
                out[i] = r
            else:
                out[i] = {"version": _u64x2(r.get(XA_VERSION)),
                          "dirty": _u64x2(r.get(XA_DIRTY))}
        return out

    async def _good_rows(self, loc: Loc) -> list[int]:
        """Up children with the quorum-best version (clean preferred)."""
        ups = self._up_idx()
        meta = await self._get_meta(ups, loc)
        vals = {i: m for i, m in meta.items()
                if not isinstance(m, BaseException)}
        if not vals:
            raise FopError(errno.ENOTCONN, "no readable children")
        clean = {i: m for i, m in vals.items() if m["dirty"] == (0, 0)}
        pool = clean or vals
        best = max(m["version"] for m in pool.values())
        return [i for i, m in pool.items() if m["version"] == best]

    def _read_child(self, candidates: list[int], gfid: bytes) -> int:
        mode = self.opts["read-hash-mode"]
        if not candidates:
            raise FopError(errno.ENOTCONN, "no consistent child")
        if mode == "first-up":
            return candidates[0]
        if mode == "gfid-hash":
            return candidates[int.from_bytes(gfid[-4:], "big")
                              % len(candidates)]
        self._rr = (self._rr + 1) % len(candidates)
        return candidates[self._rr]

    # -- transaction locks (same skeleton as EC) ---------------------------

    async def _inodelk_wind(self, loc: Loc, ltype: str) -> list[int]:
        if self._locks_supported is False:
            return []
        xd = {"lk-owner": self._lk_owner}
        locked: list[int] = []
        try:
            for i in self._up_idx():
                try:
                    await self.children[i].inodelk(
                        "afr.transaction", loc, "lock", ltype, 0, -1, xd)
                    locked.append(i)
                except FopError as e:
                    if e.err == errno.EOPNOTSUPP:
                        continue
                    raise
        except FopError:
            await self._inodelk_unwind(loc, locked)
            raise
        if self._locks_supported is None:
            self._locks_supported = bool(locked)
        return locked

    async def _inodelk_unwind(self, loc: Loc, locked: list[int]) -> None:
        xd = {"lk-owner": self._lk_owner}
        for i in locked:
            try:
                await self.children[i].inodelk(
                    "afr.transaction", loc, "unlock", "wr", 0, -1, xd)
            except FopError:
                pass

    class _Txn:
        def __init__(self, afr: "ReplicateLayer", loc: Loc, gfid: bytes,
                     ltype: str = "wr"):
            self.afr = afr
            self.loc = loc
            self.gfid = gfid
            self.ltype = ltype
            self.locked: list[int] = []
            self.local = ltype == "wr" or afr._locks_supported is False

        async def __aenter__(self):
            if self.local:
                await self.afr._lock(self.gfid).acquire()
            try:
                self.locked = await self.afr._inodelk_wind(self.loc,
                                                           self.ltype)
            except BaseException:
                if self.local:
                    self.afr._lock(self.gfid).release()
                raise
            if not self.locked and not self.local:
                self.local = True
                await self.afr._lock(self.gfid).acquire()
            return self

        async def __aexit__(self, *exc):
            await self.afr._inodelk_unwind(self.loc, self.locked)
            if self.local:
                self.afr._lock(self.gfid).release()
            return False

    # -- namespace fops ----------------------------------------------------

    async def _all(self, op: str, *args, **kw):
        res = await self._dispatch(self._up_idx(), op, lambda i: (args, kw))
        good = self._combine(res)
        return next(iter(good.values()))

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "lookup",
                                   lambda i: ((loc, xdata), {}))
        good = self._combine(res, min_ok=1)
        return next(iter(good.values()))

    async def stat(self, loc: Loc, xdata: dict | None = None):
        rows = await self._good_rows(loc)
        return await self.children[rows[0]].stat(loc, xdata)

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        return await self.stat(Loc(fd.path, gfid=fd.gfid), xdata)

    async def mkdir(self, loc: Loc, mode: int = 0o755,
                    xdata: dict | None = None):
        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        return await self._all("mkdir", loc, mode, xdata)

    async def mknod(self, loc: Loc, mode: int = 0o644, rdev: int = 0,
                    xdata: dict | None = None):
        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        return await self._all("mknod", loc, mode, rdev, xdata)

    async def symlink(self, target: str, loc: Loc, xdata: dict | None = None):
        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        return await self._all("symlink", target, loc, xdata)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        return await self._all("unlink", loc, xdata)

    async def rmdir(self, loc: Loc, flags: int = 0,
                    xdata: dict | None = None):
        return await self._all("rmdir", loc, flags, xdata)

    async def rename(self, oldloc: Loc, newloc: Loc,
                     xdata: dict | None = None):
        return await self._all("rename", oldloc, newloc, xdata)

    async def link(self, oldloc: Loc, newloc: Loc,
                   xdata: dict | None = None):
        return await self._all("link", oldloc, newloc, xdata)

    async def readlink(self, loc: Loc, xdata: dict | None = None):
        rows = await self._good_rows(loc)
        return await self.children[rows[0]].readlink(loc, xdata)

    async def setattr(self, loc: Loc, attrs: dict, valid: int = 0,
                      xdata: dict | None = None):
        return await self._all("setattr", loc, attrs, valid, xdata)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        if any(k.startswith("trusted.afr.") for k in xattrs):
            raise FopError(errno.EPERM, "reserved xattr namespace")
        return await self._all("setxattr", loc, xattrs, flags, xdata)

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        rows = await self._good_rows(loc)
        out = await self.children[rows[0]].getxattr(loc, name, xdata)
        return {k: v for k, v in out.items()
                if not k.startswith("trusted.afr.")} if name is None else out

    async def removexattr(self, loc: Loc, name: str,
                          xdata: dict | None = None):
        if name.startswith("trusted.afr."):
            raise FopError(errno.EPERM, "reserved xattr namespace")
        return await self._all("removexattr", loc, name, xdata)

    async def statfs(self, loc: Loc, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "statfs",
                                   lambda i: ((loc, xdata), {}))
        good = self._combine(res, min_ok=1)
        return min(good.values(), key=lambda s: s["bavail"] * s["bsize"])

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "opendir",
                                   lambda i: ((loc, xdata), {}))
        good = self._combine(res, min_ok=1)
        fd = FdObj(next(iter(good.values())).gfid, path=loc.path)
        fd.ctx_set(self, AfrFdCtx(dict(good), 0))
        return fd

    def _child_fd(self, fd: FdObj, i: int) -> FdObj:
        ctx: AfrFdCtx | None = fd.ctx_get(self)
        if ctx is None or ctx.child_fds.get(i) is None:
            return FdObj(fd.gfid, fd.flags, path=fd.path, anonymous=True)
        return ctx.child_fds[i]

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        for i in self._up_idx():
            try:
                return await self.children[i].readdir(
                    self._child_fd(fd, i), size, offset, xdata)
            except FopError:
                continue
        raise FopError(errno.ENOTCONN, "no child for readdir")

    async def readdirp(self, fd: FdObj, size: int = 0, offset: int = 0,
                       xdata: dict | None = None):
        for i in self._up_idx():
            try:
                return await self.children[i].readdirp(
                    self._child_fd(fd, i), size, offset, xdata)
            except FopError:
                continue
        raise FopError(errno.ENOTCONN, "no child for readdirp")

    # -- open / create -----------------------------------------------------

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        xdata = dict(xdata or {})
        xdata.setdefault("gfid-req", gfid_new())
        res = await self._dispatch(self._up_idx(), "create",
                                   lambda i: ((loc, flags, mode, xdata), {}))
        good = self._combine(res)
        child_fds = {i: r[0] for i, r in good.items()}
        ia = next(iter(good.values()))[1]
        zero = {XA_VERSION: _pack_u64x2(0, 0), XA_DIRTY: _pack_u64x2(0, 0)}
        await self._dispatch(list(good), "setxattr",
                             lambda i: ((loc, dict(zero)), {}))
        fd = FdObj(ia.gfid, flags, path=loc.path)
        fd.ctx_set(self, AfrFdCtx(child_fds, flags))
        return fd, ia

    async def open(self, loc: Loc, flags: int = 0, xdata: dict | None = None):
        res = await self._dispatch(self._up_idx(), "open",
                                   lambda i: ((loc, flags), {}))
        good = self._combine(res, min_ok=1)
        fd = FdObj(next(iter(good.values())).gfid, flags, path=loc.path)
        fd.ctx_set(self, AfrFdCtx(dict(good), flags))
        return fd

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        await self._dispatch(self._up_idx(), "flush",
                             lambda i: ((self._child_fd(fd, i),), {}))
        return {}

    async def fsync(self, fd: FdObj, datasync: int = 0,
                    xdata: dict | None = None):
        res = await self._dispatch(
            self._up_idx(), "fsync",
            lambda i: ((self._child_fd(fd, i), datasync), {}))
        self._combine(res)
        return {}

    async def release(self, fd: FdObj):
        ctx: AfrFdCtx | None = fd.ctx_del(self)
        if ctx:
            for i, cfd in ctx.child_fds.items():
                rel = getattr(self.children[i], "release", None)
                if rel:
                    try:
                        await rel(cfd)
                    except Exception:
                        pass

    # -- data path ---------------------------------------------------------

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        loc = Loc(fd.path, gfid=fd.gfid)
        candidates = await self._good_rows(loc)
        last: FopError | None = None
        for _ in range(len(candidates)):
            i = self._read_child(candidates, fd.gfid)
            try:
                return await self.children[i].readv(
                    self._child_fd(fd, i), size, offset, xdata)
            except FopError as e:
                last = e
                candidates = [c for c in candidates if c != i]
                if not candidates:
                    break
        raise last or FopError(errno.ENOTCONN, "read failed")

    async def _write_txn(self, loc: Loc, gfid: bytes, op: str, argfn):
        """The replicated write transaction (afr-transaction.c:1087,629):
        pre-op dirty on all up replicas, dispatch, quorum, post-op
        version bump on the good ones — dirty is released only when
        EVERY replica took the write (a partial success keeps the mark,
        and the brick-side pending-index entry, for the shd)."""
        async with self._Txn(self, loc, gfid, "wr"):
            idxs = self._up_idx()
            await self._dispatch(
                idxs, "xattrop",
                lambda i: ((loc, "add64",
                            {XA_DIRTY: _pack_u64x2(1, 0)}), {}))
            res = await self._dispatch(idxs, op, argfn)
            good = [i for i, r in res.items()
                    if not isinstance(r, BaseException)]
            if len(good) < self._quorum():
                raise FopError(errno.EIO,
                               f"{op} quorum lost ({len(good)}/{self.n})")
            post = {XA_VERSION: _pack_u64x2(1, 0)}
            if len(good) == self.n:
                post[XA_DIRTY] = _pack_u64x2(-1 & 0xFFFFFFFFFFFFFFFF, 0)
            await self._dispatch(
                good, "xattrop", lambda i: ((loc, "add64", dict(post)), {}))
            return next(r for i, r in res.items() if i in good)

    async def writev(self, fd: FdObj, data: bytes, offset: int,
                     xdata: dict | None = None):
        loc = Loc(fd.path, gfid=fd.gfid)
        return await self._write_txn(
            loc, fd.gfid, "writev",
            lambda i: ((self._child_fd(fd, i), data, offset), {}))

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        ia, _ = await self.lookup(loc)
        return await self._write_txn(loc, ia.gfid, "truncate",
                                     lambda i: ((loc, size, xdata), {}))

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        return await self.truncate(Loc(fd.path, gfid=fd.gfid), size, xdata)

    async def fallocate(self, fd: FdObj, mode: int, offset: int,
                        length: int, xdata: dict | None = None):
        return await self._write_txn(
            Loc(fd.path, gfid=fd.gfid), fd.gfid, "fallocate",
            lambda i: ((self._child_fd(fd, i), mode, offset, length), {}))

    async def discard(self, fd: FdObj, offset: int, length: int,
                      xdata: dict | None = None):
        return await self._write_txn(
            Loc(fd.path, gfid=fd.gfid), fd.gfid, "discard",
            lambda i: ((self._child_fd(fd, i), offset, length), {}))

    async def zerofill(self, fd: FdObj, offset: int, length: int,
                       xdata: dict | None = None):
        return await self._write_txn(
            Loc(fd.path, gfid=fd.gfid), fd.gfid, "zerofill",
            lambda i: ((self._child_fd(fd, i), offset, length), {}))

    async def seek(self, fd: FdObj, offset: int, what: str = "data",
                   xdata: dict | None = None):
        loc = Loc(fd.path, gfid=fd.gfid)
        candidates = await self._good_rows(loc)
        last: FopError | None = None
        for i in candidates:
            try:
                return await self.children[i].seek(
                    self._child_fd(fd, i), offset, what, xdata)
            except FopError as e:
                if e.err == errno.ENXIO:
                    raise
                last = e
        raise last or FopError(errno.ENOTCONN, "no child for seek")

    # -- heal --------------------------------------------------------------

    async def heal_info(self, loc: Loc) -> dict:
        """Heal direction by committed version, never clean-ness: a brick
        that slept through the write is spotlessly clean AND stale —
        electing it as source would heal new data away.  The highest
        post-op version wins (afr_selfheal_find_direction semantics:
        pending counters point away from sources); dirty marks on the
        winners are expected after a partial write and do not disqualify
        them."""
        meta = await self._get_meta(list(range(self.n)), loc)
        versions = {}
        for i, m in meta.items():
            versions[i] = None if isinstance(m, BaseException) else \
                (m["version"], m["dirty"])
        ok = {i: v for i, v in versions.items() if v is not None}
        if not ok:
            raise FopError(errno.ENOTCONN, "no bricks reachable")
        best = max(v[0] for v in ok.values())
        good = [i for i, v in ok.items() if v[0] == best]
        bad = [i for i in range(self.n) if i not in good]
        dirty = any(v[1] != (0, 0) for v in ok.values())
        return {"good": good, "bad": bad, "version": best,
                "per_brick": versions, "dirty": dirty}

    async def heal_file(self, path: str) -> dict:
        loc = Loc(path)
        info = await self.heal_info(loc)
        good, bad = info["good"], info["bad"]
        if not good:
            raise FopError(errno.EIO, "no heal source")
        fav = self.opts["favorite-child"]
        src = fav if fav in good else good[0]
        if not bad:
            if not info.get("dirty"):
                return {"healed": [], "skipped": True}
            # Dirty with equal versions can hide diverged content (a
            # quorum-lost write data-lands on some replicas before the
            # fop fails, with no post-op anywhere).  Re-copy from one
            # source instead of just unmarking (afr data heal re-runs
            # whenever dirty is set).
            bad = [i for i in good if i != src]
            good = [src]
            if not bad:
                return {"healed": [], "skipped": True}
        ia, _ = await self.lookup(loc)
        async with self._Txn(self, loc, ia.gfid, "wr"):
            src_ia = await self.children[src].stat(loc)
            # ensure file exists on bad bricks
            for i in bad:
                try:
                    await self.children[i].lookup(loc)
                except FopError:
                    try:
                        await self.children[i].mknod(
                            loc, src_ia.mode, 0, {"gfid-req": ia.gfid})
                    except FopError:
                        continue
            window = int(self.opts["self-heal-window-size"])
            sfd = FdObj(ia.gfid, path=path, anonymous=True)
            off = 0
            from ..features.bit_rot_stub import HEAL_WRITE

            while off < src_ia.size:
                chunk = await self.children[src].readv(
                    sfd, min(window, src_ia.size - off), off)
                await self._dispatch(
                    bad, "writev",
                    lambda i: ((FdObj(ia.gfid, path=path, anonymous=True),
                                chunk, off),
                               {"xdata": {HEAL_WRITE: True}}))
                off += len(chunk)
            await self._dispatch(bad, "truncate",
                                 lambda i: ((loc, src_ia.size), {}))
            meta = await self._get_meta([src], loc)
            fix = {XA_VERSION: _pack_u64x2(*meta[src]["version"]),
                   XA_DIRTY: _pack_u64x2(0, 0)}
            await self._dispatch(bad, "setxattr",
                                 lambda i: ((loc, dict(fix)), {}))
            await self._dispatch(good, "setxattr", lambda i: (
                (loc, {XA_DIRTY: _pack_u64x2(0, 0)}), {}))
            return {"healed": bad, "skipped": False, "source": src}

    async def heal_entry(self, path: str = "/") -> dict:
        """Directory entry heal: union the listings, copy missing entries
        from any brick that has them (afr-self-heal-entry.c)."""
        loc = Loc(path)
        listings: dict[int, set[str]] = {}
        for i in self._up_idx():
            try:
                fd = await self.children[i].opendir(loc)
                names = await self.children[i].readdir(fd)
                listings[i] = {n for n, _ in names}
            except FopError:
                continue
        union: set[str] = set().union(*listings.values()) if listings else set()
        created = []
        for name in union:
            child_path = path.rstrip("/") + "/" + name
            have = [i for i, names in listings.items() if name in names]
            missing = [i for i in listings if name not in listings[i]]
            if not missing:
                continue
            src = have[0]
            src_ia = await self.children[src].stat(Loc(child_path))
            for i in missing:
                try:
                    if src_ia.ia_type is IAType.DIR:
                        await self.children[i].mkdir(
                            Loc(child_path), src_ia.mode,
                            {"gfid-req": src_ia.gfid})
                    else:
                        await self.children[i].mknod(
                            Loc(child_path), src_ia.mode, 0,
                            {"gfid-req": src_ia.gfid})
                    created.append((i, name))
                except FopError:
                    continue
            if src_ia.ia_type is not IAType.DIR:
                await self.heal_file(child_path)
        return {"created": created}

    def dump_private(self) -> dict:
        return {"replicas": self.n, "up": self.up,
                "quorum": self._quorum(),
                "read_hash_mode": self.opts["read-hash-mode"]}
